"""Vmapped constant-config sweeps: one dispatch checks K models.

Real verification practice runs a PORTFOLIO of small models per spec -
the same module under many MC.cfg constant overrides (PAPER.md §L4's
configuration layer; the TLA+ Trifecta workflow in PAPERS.md runs
dozens per proof effort).  Checking them one at a time wastes both the
compile (each override bakes new literals into the step) and the
device (a tiny model leaves the chip idle).  This module batches the
override layer itself:

* **Swept constants become state fields.**  `sweep_backend` compiles
  the module ONCE with each swept CONSTANT promoted to a read-only
  codec field (LaneCompiler `sweep_vars`): expressions read the value
  from the state vector at runtime, every lane passes it through
  verbatim, and each configuration's Init seeds the field with its
  value.  Within one run the field never changes, so a config's state
  graph is isomorphic to the baked-constant run's - verdict, depth and
  every generated/distinct/per-action counter are IDENTICAL numbers
  (fingerprints differ: the encoding carries the extra field).

* **The config axis vmaps.**  K per-config carries (one `init_fn`
  seeding each, through the production packing/fpset/init-invariant
  path) stack into one batched carry and `vmap(run_fn)` drives all K
  BFS loops in a single device dispatch.  jax's batched while_loop
  freezes each lane at its own fixpoint, so every lane's final carry
  is bit-for-bit what a sequential run of the same compiled engine
  produces (`run_sequential` is that baseline; tests pin the equality
  down to the fpset table words).

Supported sweep class: integer scalar CONSTANTs used as VALUES (guards,
arithmetic, comparisons).  A constant that determines shapes - set
universes, quantifier domains, sequence caps - cannot ride a state
field; the compiler then needs a static value and raises CompileError,
loudly, at class-build time (never a silent misrun).  Load the anchor
model with each swept constant at its domain MAX (`load_anchored`) so
the inferred integer ranges cover the whole class; a config whose
values escape the anchored ranges halts with the codec range trap.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.backend import SpecBackend
from ..engine.bfs import (
    CheckResult,
    make_backend_engine,
    result_from_carry,
)
from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED
from ..struct.backend import struct_viol_names
from ..struct.codec import StructCodec
from ..struct.compile import LaneCompiler
from ..struct.loader import StructModel, load
from ..struct.shapes import SInt, infer_shapes, typeok_hints

DEFAULT_WIDTH = 4  # configs per batched dispatch (pad-to-width)


class SweepError(ValueError):
    pass


def load_anchored(cfg_path: str,
                  params: Dict[str, Tuple[int, int]],
                  const_overrides: Optional[Dict[str, object]] = None,
                  ) -> StructModel:
    """Load the model with every swept constant at its domain MAX (the
    shape anchor: inferred integer ranges must cover the class).

    const_overrides carries a job's FIXED (non-swept) constants: they
    bake into the anchor like any cfg value, so the model's digest,
    canonical constants - and therefore `class_key` and every
    `config_inits` fallback - all reflect them.  Swept names in the
    dict are ignored (the anchor pins those to the domain max)."""
    overrides = {k: v for k, v in (const_overrides or {}).items()
                 if k not in params}
    overrides.update({c: int(hi) for c, (_lo, hi) in params.items()})
    return load(cfg_path, const_overrides=overrides)


def class_key(model: StructModel,
              params: Dict[str, Tuple[int, int]]) -> tuple:
    """The constants-CLASS cache key: spec digest + canonical constants
    WITHOUT the swept names + their domains.  Every configuration of
    the class maps to the same key, which is the whole point - the
    EnginePool holds one warm engine per class, not per config."""
    from ..struct.backend import canonical_constants

    consts = canonical_constants(model)
    for c in params:
        consts.pop(c, None)
    return (
        model.source_digest,
        tuple(sorted(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in consts.items()
        )),
        tuple(model.invariants),
        tuple((c, int(lo), int(hi))
              for c, (lo, hi) in sorted(params.items())),
    )


def sweep_backend(model: StructModel,
                  params: Dict[str, Tuple[int, int]],
                  check_deadlock: bool = True) -> SpecBackend:
    """Compile `model` with the swept constants as runtime state fields
    - the constants-class step every configuration shares."""
    system = model.system
    names = tuple(sorted(params))
    for c in names:
        if c not in model.constants:
            raise SweepError(f"swept name {c!r} is not a CONSTANT")
        if not isinstance(model.constants[c], int) or isinstance(
            model.constants[c], bool
        ):
            raise SweepError(
                f"swept constant {c!r} must be an integer scalar, "
                f"got {model.constants[c]!r}"
            )
        lo, hi = params[c]
        if not (lo <= model.constants[c] <= hi):
            raise SweepError(
                f"anchor value {model.constants[c]} of {c!r} outside "
                f"its domain [{lo}, {hi}] (load the anchor model at "
                "the domain max: load_anchored)"
            )
    hints = typeok_hints(system.ev, model.invariants, system.variables)
    var_shapes = infer_shapes(system.ev, system.variables,
                              system.init_ast, system.next_ast,
                              hints=hints)
    for c in names:
        lo, hi = params[c]
        var_shapes[c] = SInt(int(lo), int(hi))
    ext_vars = tuple(system.variables) + names
    cdc = StructCodec(ext_vars, var_shapes)
    compiler = LaneCompiler(system.ev, ext_vars, var_shapes, cdc,
                            sweep_vars=frozenset(names))
    batch_step = compiler.build_step(system.next_ast)
    inv_fns = [
        compiler.build_invariant(ast) for ast in model.invariants.values()
    ]
    F = cdc.n_fields

    jax.eval_shape(batch_step, jax.ShapeDtypeStruct((1, F), jnp.int32))
    labels: List[str] = list(compiler.labels)
    action_names: Tuple[str, ...] = tuple(sorted(set(labels)))
    lane_action = jnp.asarray(
        [action_names.index(x) for x in labels], jnp.int32
    )

    def step(vec):
        succs, valid, ovf, afail = batch_step(vec[None])
        return succs[0], valid[0], lane_action, afail[0], ovf[0]

    def inv_check(vec):
        bits = jnp.int32(0)
        for k, fn in enumerate(inv_fns):
            bits = bits | (fn(vec[None])[0].astype(jnp.int32) << k)
        return bits

    def initial_vectors():
        # the anchor configuration's Init set (engine geometry probe +
        # AOT compile input; per-config seeds come from config_inits)
        return config_inits(
            model, params, {c: model.constants[c] for c in names}, cdc
        )

    from ..struct.backend import VIOL_INVARIANT_BASE

    return SpecBackend(
        cdc=cdc,
        step=step,
        n_lanes=len(labels),
        inv_check=inv_check,
        inv_codes=tuple(
            VIOL_INVARIANT_BASE + k for k in range(len(model.invariants))
        ),
        initial_vectors=initial_vectors,
        labels=action_names,
        viol_names=struct_viol_names(model),
        lane_action=lane_action,
        check_deadlock=check_deadlock,
    )


def config_inits(model: StructModel,
                 params: Dict[str, Tuple[int, int]],
                 values: Dict[str, int],
                 cdc: StructCodec) -> np.ndarray:
    """One configuration's Init set as [n0, F] field vectors of the
    class codec: enumerate Init host-side under the config's CONSTANT
    values, then append the swept fields."""
    names = tuple(sorted(params))
    missing = [c for c in names if c not in values]
    if missing:
        raise SweepError(f"config misses swept constants {missing}")
    consts = dict(model.constants)
    consts.update({c: int(values[c]) for c in names})
    sysk = model.system.with_constants(consts)
    tail = tuple(int(values[c]) for c in names)
    rows = [cdc.encode(st + tail) for st in sysk.initial_states()]
    if not rows:
        raise SweepError(f"config {values!r} has an empty Init set")
    return np.stack(rows)


class SweepEngine:
    """A warm constants-class engine: one compiled step + one batched
    AOT executable that checks up to `width` configurations per device
    dispatch.  Build once per class (the expensive part), `run` per
    submitted batch (the cheap part) - the EnginePool holds these."""

    def __init__(
        self,
        model: StructModel,
        params: Dict[str, Tuple[int, int]],
        chunk: int = 64,
        queue_capacity: int = 1 << 10,
        fp_capacity: int = 1 << 12,
        fp_index: int = DEFAULT_FP_INDEX,
        seed: int = DEFAULT_SEED,
        check_deadlock: bool = True,
        width: int = DEFAULT_WIDTH,
        sort_free: bool = None,
        deferred: bool = None,
    ):
        from ..struct.cache import enable_persistent_cache

        enable_persistent_cache()  # class compiles persist like struct's
        self.model = model
        self.params = {c: (int(lo), int(hi))
                       for c, (lo, hi) in params.items()}
        self.width = max(1, int(width))
        self.fp_capacity = fp_capacity
        self.backend = sweep_backend(model, self.params, check_deadlock)
        # donate=False: the vmap traces THROUGH run_fn (donation would
        # alias a carry the sequential parity baseline reuses), and the
        # JAXTLC_DEBUG_DONATION poisoner must not wrap a vmapped callee
        # NOTE on sort_free under vmap: lax.cond batches to both
        # branches, so a sort-free sweep engine pays the sorted
        # fallback alongside the slab - correct, just not the perf win
        # (auto keeps sweeps sorted at their small default chunks)
        init_fn, run_fn, _ = make_backend_engine(
            self.backend, chunk, queue_capacity, fp_capacity,
            fp_index, seed, check_deadlock=check_deadlock, donate=False,
            sort_free=sort_free, deferred=deferred,
        )
        # jitted seeding: an eager init_fn recompiles its fpset
        # while_loop per call; under jit the (per-Init-set-shape)
        # compile happens once and warm batches run compile-free
        self._init_jit = jax.jit(init_fn)
        self._run_fn = run_fn
        self._vrun = jax.jit(jax.vmap(run_fn))
        self._aot = None
        self._aot_seq = None

    # -- carries -----------------------------------------------------------

    def carry_for(self, values: Dict[str, int]):
        """A fresh engine carry seeded with one configuration's Init."""
        return self._init_jit(
            config_inits(self.model, self.params, values,
                         self.backend.cdc)
        )

    def _stack(self, configs: List[Dict[str, int]]):
        if not configs:
            raise SweepError("empty config batch")
        if len(configs) > self.width:
            raise SweepError(
                f"{len(configs)} configs > sweep width {self.width} "
                "(the scheduler slices batches to width)"
            )
        # pad to the compiled width by repeating the last config: the
        # pad lanes are pure discarded compute, so the AOT executable
        # is one shape per class, not one per batch size
        pad = configs + [configs[-1]] * (self.width - len(configs))
        carries = [self.carry_for(v) for v in pad]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *carries)

    def _result(self, carry, wall_s: float) -> CheckResult:
        return result_from_carry(
            carry, wall_s, fp_capacity=self.fp_capacity,
            labels=self.backend.labels,
            viol_names=struct_viol_names(self.model),
        )

    # -- execution ---------------------------------------------------------

    def run(self, configs: List[Dict[str, int]]) -> List[CheckResult]:
        """Check up to `width` configurations in ONE device dispatch;
        per-config results in submission order.  wall_s on every result
        is the whole batch's dispatch wall (one dispatch = one wall)."""
        stacked = self._stack(configs)
        if self._aot is None:
            self._aot = self._vrun.lower(stacked).compile()
        t0 = time.time()
        out = jax.block_until_ready(self._aot(stacked))
        wall = time.time() - t0
        return [
            self._result(jax.tree.map(lambda x: x[k], out), wall)
            for k in range(len(configs))
        ]

    def run_sequential(self,
                       configs: List[Dict[str, int]]) -> List[CheckResult]:
        """The parity baseline: the SAME compiled step, one config at a
        time (K dispatches).  tests pin run() bit-for-bit against this,
        fpset table words included."""
        results = []
        for values in configs:
            carry = self.carry_for(values)
            if self._aot_seq is None:
                self._aot_seq = self._run_fn.lower(carry).compile()
            t0 = time.time()
            out = jax.block_until_ready(self._aot_seq(carry))
            results.append(self._result(out, time.time() - t0))
        return results
