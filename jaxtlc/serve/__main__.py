"""``python -m jaxtlc.serve`` - start the checking service.

Options size the pool and the batch axis; --tiny is the self-contained
smoke (start on an ephemeral port, submit a warm/cold job pair through
the real HTTP surface, assert pool reuse + zero-compile warm submit;
tools/loadgen.py --tiny is the heavier load-shaped version wired into
tier-1).
"""

from __future__ import annotations

import sys
import time


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="jaxtlc.serve")
    p.add_argument("root", nargs="?", default=None,
                   help="runs directory (journals + job artifacts; "
                        "default: a fresh temp dir)")
    p.add_argument("--port", type=int, default=8791)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--pool-cap", type=int, default=8,
                   help="warm AOT engines held (LRU beyond)")
    p.add_argument("--sweep-width", type=int, default=None,
                   help="configs per batched sweep dispatch")
    p.add_argument("--large-fpcap", type=int, default=None,
                   help="fp_capacity above which a job routes through "
                        "the resil supervisor instead of the pool")
    p.add_argument("--prewarm", default="", metavar="SPEC:CFG[,...]",
                   help="comma-separated cfg paths (or SPEC:CFG pairs) "
                        "to compile into the pool at startup, so the "
                        "FIRST submit of each rides the warm path "
                        "(compiled at the pooled-path default geometry "
                        "in a background thread; progress on /pool)")
    p.add_argument("--queue-bound", type=int, default=None,
                   help="admission bound on queued jobs (submits "
                        "beyond it get 429 + Retry-After)")
    p.add_argument("--tenant-quota", type=int, default=None,
                   help="per-tenant bound on queued jobs (fair-share "
                        "admission; dequeue is weighted round-robin "
                        "between tenants regardless)")
    p.add_argument("--tiny", action="store_true",
                   help="smoke: serve + submit + assert warm reuse, "
                        "then exit")
    args = p.parse_args(argv)
    from .server import start_server

    if args.tiny:
        return _tiny()
    srv = start_server(
        args.root, port=args.port, host=args.host,
        pool_capacity=args.pool_cap, sweep_width=args.sweep_width,
        large_fpcap=args.large_fpcap,
        prewarm=[s for s in args.prewarm.split(",") if s],
        queue_bound=args.queue_bound, tenant_quota=args.tenant_quota,
    )
    print(f"jaxtlc checking service at {srv.url} "
          f"(POST /jobs, DELETE /jobs/<id>; GET /jobs /pool /health "
          f"/runs /metrics /events; runs dir {srv.root}; ctrl-c exits)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
        return 0


_TINY_SPEC = """---- MODULE ServeTiny ----
EXTENDS Naturals
CONSTANTS MAX
VARIABLES x

Init == x = 0

Up == /\\ x < MAX
      /\\ x' = x + 1

Reset == /\\ x = MAX
         /\\ x' = 0

Next == Up \\/ Reset

Spec == Init /\\ [][Next]_x

InRange == x <= MAX
====
"""

_TINY_CFG = """CONSTANT MAX = 3
SPECIFICATION
Spec
INVARIANT
InRange
"""


def _tiny() -> int:
    """Serve + submit a cold/warm pair end-to-end over real HTTP:
    second submit must be a pool hit with ZERO fresh XLA compiles."""
    from . import client
    from .pool import xla_compiles
    from .server import start_server

    srv = start_server()
    try:
        opts = dict(chunk=16, qcap=256, fpcap=1024)
        cold = client.check(srv.url, _TINY_SPEC, _TINY_CFG,
                            name="tiny-cold", options=opts)
        assert cold["state"] == "done", cold
        assert cold["result"]["verdict"] == "ok", cold
        assert cold["result"]["engine"] == "pool", cold
        pre = xla_compiles()
        warm = client.check(srv.url, _TINY_SPEC, _TINY_CFG,
                            name="tiny-warm", options=opts)
        fresh = xla_compiles() - pre
        assert warm["result"]["pool_hit"] is True, warm
        assert fresh == 0, f"warm submit paid {fresh} XLA compiles"
        assert warm["result"]["generated"] == cold["result"]["generated"]
        stats = client.pool_stats(srv.url)
        assert stats["pool"]["hits"] >= 1, stats
        # two job journals + the scheduler's own control-plane journal
        runs = client._get(srv.url + "/runs")["runs"]
        assert len(runs) == 3, runs
        assert any(r["run"] == "sched" for r in runs), runs
        h = client.health(srv.url)
        assert h["status"] == "ok" and h["queued"] == 0, h
        assert h["counters"]["admitted"] >= 2, h
    finally:
        srv.shutdown()
    print("serve tiny OK: cold compile -> warm resubmit with 0 fresh "
          "XLA compiles, verdicts ok, 2 job runs + sched journal "
          "registered, /health ok")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main(sys.argv[1:]))
