"""jaxtlc.serve - checking as a service (ROADMAP #4).

A persistent, multi-job checking service assembled from the pieces
earlier rounds built: the struct compile cache (PR 3) becomes a warm
AOT `EnginePool` (serve.pool), the run journal + monitoring server
(PR 5/8) become the per-job telemetry surface (serve.server subclasses
obs.serve), the MC.cfg constant-override layer becomes a vmapped batch
axis (serve.sweep), and `jaxtlc.api.run_check` - the engine-as-a-
library refactor this package forced - runs the large jobs under the
resil supervisor (serve.scheduler).

``python -m jaxtlc.serve`` starts the server; ``jaxtlc.serve.client``
submits; ``tools/loadgen.py`` load-tests the warm path.
"""

from .pool import CompileMeter, EnginePool, xla_compiles  # noqa: F401
from .scheduler import Job, JobError, Scheduler  # noqa: F401
from .server import CheckServer, start_server  # noqa: F401
from .sweep import (  # noqa: F401
    SweepEngine,
    SweepError,
    class_key,
    load_anchored,
    sweep_backend,
)
