"""jaxtlc: a TPU-native TLA+ exhaustive model-checking framework.

Executes the KubeAPI action system (reference: JohnStrunk/tla-kubernetes)
with a vmapped next-state kernel, device-resident fingerprint dedup, and a
sharded multi-device BFS - reproducing the reference TLC run's verdicts and
statistics exactly.  See SURVEY.md for the architecture map.
"""

__version__ = "0.2.0"
