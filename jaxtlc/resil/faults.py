"""Deterministic fault injection for the run supervisor.

Every recovery path the supervisor claims (auto-regrow, retry-with-
backoff, SIGTERM drain, generation fallback after a torn checkpoint) is
proven by an INJECTED fault whose recovered run must match the clean
run's final statistics exactly (tests/test_resil.py, tools/chaos.py).
A FaultPlan is a fixed schedule - "fail the 2nd disk write", "raise a
transient error when segment 3 starts", "deliver SIGTERM at segment 2",
"truncate the checkpoint written at segment 1" - threaded through the
supervisor's hooks, so a chaos run is reproducible bit-for-bit.

The plan DSL (tools/chaos.py `--plan`):

    write_fail@N    raise OSError on the Nth checkpoint write (1-based)
    truncate@N      after the Nth checkpoint write succeeds, truncate the
                    published file mid-byte (simulates the torn write the
                    fsync+generation scheme defends against)
    transient@K     raise TransientFault when segment K starts (0-based;
                    the supervisor's retry/backoff path must absorb it)
    sigterm@K       deliver a real SIGTERM to this process when segment K
                    starts (the preemption drain path)
    alloc_fail@N    deny the Nth regrow allocation probe (1-based) with
                    an injected RESOURCE_EXHAUSTED - the degradation
                    ladder must route fpset growth to the host spill
                    tier instead of crashing mid-migration
    spill_fail@N    raise OSError on the Nth host spill write (the
                    device-table flush into the SpillStore, 1-based);
                    the ladder must degrade to checkpoint + exit 75
    runner_die@N    raise TransientFault when the serve scheduler's Nth
                    dispatch starts (1-based) - the scheduler's retry
                    classification must absorb it (ISSUE 17)
    slow_dispatch@N sleep before the serve scheduler's Nth dispatch
                    (1-based) - the deterministic window the deadline
                    reaper / admission tests need (ISSUE 17)

Entries are comma-separated: "transient@1,sigterm@3".  Each entry fires
at most once.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import time
from typing import Callable, FrozenSet, Optional


class TransientFault(RuntimeError):
    """An injected stand-in for a transient device/XLA error (the class of
    failure the supervisor's retry-with-backoff absorbs)."""


class AllocDeniedFault(MemoryError):
    """An injected stand-in for a deterministic RESOURCE_EXHAUSTED
    device-allocation failure (the class retry can NEVER fix - the
    supervisor's degradation ladder must absorb it instead).  The
    message carries the XLA status string so the supervisor's
    classify-by-message path is exercised, not bypassed."""

    def __init__(self, detail: str):
        super().__init__(f"RESOURCE_EXHAUSTED: {detail} (injected)")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """A deterministic fault schedule.  All members are sets of 1-based
    write ordinals / 0-based segment ordinals; empty = no fault."""

    write_fail: FrozenSet[int] = frozenset()
    truncate: FrozenSet[int] = frozenset()
    transient: FrozenSet[int] = frozenset()
    sigterm: FrozenSet[int] = frozenset()
    alloc_fail: FrozenSet[int] = frozenset()
    spill_fail: FrozenSet[int] = frozenset()
    runner_die: FrozenSet[int] = frozenset()
    slow_dispatch: FrozenSet[int] = frozenset()

    @staticmethod
    def parse(spec: str) -> "FaultPlan":
        """Parse the chaos DSL ("write_fail@2,transient@1,sigterm@3")."""
        kinds = {"write_fail": set(), "truncate": set(),
                 "transient": set(), "sigterm": set(),
                 "alloc_fail": set(), "spill_fail": set(),
                 "runner_die": set(), "slow_dispatch": set()}
        for entry in filter(None, (e.strip() for e in spec.split(","))):
            try:
                kind, at = entry.split("@")
                kinds[kind].add(int(at))
            except (ValueError, KeyError):
                raise ValueError(
                    f"bad fault entry {entry!r} (want kind@N with kind in "
                    f"{sorted(kinds)})"
                )
        return FaultPlan(**{k: frozenset(v) for k, v in kinds.items()})


class FaultInjector:
    """Runtime state of one plan: counts writes/segments, fires each
    scheduled fault exactly once.  A None plan injects nothing (the
    production configuration - the hooks cost a comparison each)."""

    # how long a slow_dispatch@N fault stalls the scheduler (seconds);
    # an attribute so chaos harnesses can tighten/loosen the window
    slow_dispatch_s = 0.25

    def __init__(self, plan: Optional[FaultPlan] = None,
                 kill: Callable[[], None] = None):
        self.plan = plan or FaultPlan()
        self.writes = 0
        self.alloc_probes = 0
        self.spill_writes = 0
        self.fired = set()
        # test seam: default delivers a real SIGTERM to this process
        self._kill = kill or (
            lambda: os.kill(os.getpid(), signal.SIGTERM)
        )

    def _once(self, key) -> bool:
        if key in self.fired:
            return False
        self.fired.add(key)
        return True

    def segment_start(self, k: int) -> None:
        """Hook: the supervisor is about to run segment k (0-based)."""
        if k in self.plan.sigterm and self._once(("sigterm", k)):
            self._kill()
        if k in self.plan.transient and self._once(("transient", k)):
            raise TransientFault(f"injected transient fault at segment {k}")

    def dispatch(self, n: int) -> None:
        """Hook: the serve scheduler is about to run its nth dispatch
        (1-based).  `slow_dispatch` stalls the worker (opening the
        deterministic window the deadline/admission chaos scenarios
        need); `runner_die` kills the dispatch with a TransientFault
        the scheduler's retry classification must absorb."""
        if n in self.plan.slow_dispatch and self._once(
            ("slow_dispatch", n)
        ):
            time.sleep(self.slow_dispatch_s)
        if n in self.plan.runner_die and self._once(("runner_die", n)):
            raise TransientFault(
                f"injected runner death at dispatch {n}"
            )

    def before_write(self) -> None:
        """Hook: a checkpoint write is about to happen (counts 1-based)."""
        self.writes += 1
        if self.writes in self.plan.write_fail and self._once(
            ("write_fail", self.writes)
        ):
            raise OSError(f"injected disk-write failure #{self.writes}")

    def alloc_probe(self) -> None:
        """Hook: the supervisor is about to probe-allocate a regrown
        resource (counts 1-based).  An injected denial looks exactly
        like XLA's RESOURCE_EXHAUSTED, so the ladder's classification
        path is the one under test."""
        self.alloc_probes += 1
        if self.alloc_probes in self.plan.alloc_fail and self._once(
            ("alloc_fail", self.alloc_probes)
        ):
            raise AllocDeniedFault(
                f"regrow allocation probe #{self.alloc_probes} denied"
            )

    def spill_write(self) -> None:
        """Hook: a device-table flush into the host spill store is
        about to happen (counts 1-based)."""
        self.spill_writes += 1
        if self.spill_writes in self.plan.spill_fail and self._once(
            ("spill_fail", self.spill_writes)
        ):
            raise OSError(
                f"injected spill-write failure #{self.spill_writes}"
            )

    def after_write(self, path: str) -> None:
        """Hook: checkpoint write #self.writes published `path`."""
        if self.writes in self.plan.truncate and self._once(
            ("truncate", self.writes)
        ):
            truncate_file(path)


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Tear a published file: keep the leading `frac` of its bytes.  The
    generation fallback must then recover from the predecessor."""
    size = os.path.getsize(path)
    with open(path, "rb+") as f:
        f.truncate(max(1, int(size * frac)))
