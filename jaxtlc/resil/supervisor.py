"""The run supervisor: self-healing exhaustive runs.

TLC's production value rests on surviving long runs (periodic disk
checkpoints + `-recover`); the TPU-native engines add three failure modes
TLC does not have - fixed-capacity device containers (fpset/queue/route
buckets sized at compile time), preemptible accelerator jobs (SIGTERM is
how TPU pods die), and transient XLA/device errors.  This module wraps
the segmented drivers (engine.checkpoint / engine.sharded) in a
supervision loop that converts all three from run-killers into events:

* **The capacity degradation ladder**: a capacity halt (VIOL_FPSET_FULL
  / VIOL_QUEUE_FULL / VIOL_ROUTE_OVERFLOW) walks rungs until one holds,
  instead of the old binary regrow-or-die:

  1. **regrow** - double the saturated resource, but only after a PROBE
     ALLOCATION confirms the doubled buffer is allocatable (a
     deterministic RESOURCE_EXHAUSTED used to crash mid-migration);
     migrate the last-good carry (resil.regrow) and replay the segment -
     final statistics provably equal an uninterrupted correctly-sized
     run's.  Bounded by max_regrow.
  2. **host spill tier** (fpset saturation on unpipelined single-device
     runs) - activate engine.spill: cold fingerprints migrate to a
     host-RAM SpillStore, the device table becomes the hot tier with an
     fpset_member filter in front of the host round trip, and the run
     COMPLETES inside the device memory it has - bit-for-bit the clean
     run's counters/verdict.
  3. **chunk shrink** - halve the pop width (freeing candidate-buffer
     memory) and retry the regrow probe; repeats to a floor of 64.
     Counts/verdict are preserved; in-batch duplicate attribution may
     shift (documented in resil.regrow).
  4. **checkpoint + exit 75** - write a final generation (host tier
     included), journal an `exhausted` event with the resume command,
     and return exhausted=True (the CLI exits EXIT_INTERRUPTED).

  VIOL_SLOT_OVERFLOW (codec bit-widths too narrow) is NOT on the ladder
  - it needs a recompile - and degrades to checkpoint + actionable
  error as before.
* **Preemption safety**: SIGTERM/SIGINT finish the current segment,
  write a final checkpoint generation, and return `interrupted=True`
  (the CLI exits with EXIT_INTERRUPTED and prints the resume command).
* **Retry with backoff**: TRANSIENT errors around segment execution are
  retried from the last good carry with exponential backoff + jitter
  (deterministic, seeded) up to `retries` attempts.  Runtime errors are
  CLASSIFIED first: a RESOURCE_EXHAUSTED/OOM is deterministic - it goes
  to the ladder immediately instead of burning the whole retry budget.
* **Crash-consistent storage**: checkpoints are CRC-manifested,
  fsync'd, generation-numbered files; resume loads the newest generation
  that passes verification, falling back past a torn newest file, and
  rebuilds the engine with the geometry THE CHECKPOINT RECORDS - so a
  resume command never needs to repeat auto-grown capacities.  A
  spilling run pairs every generation with a CRC'd host-tier file
  (PATH.gNNNNNN.npz.spill); `-recover` restores BOTH tiers bit-for-bit
  or falls back to the previous intact pair.

Every recovery path is proven by fault injection (resil.faults,
tools/chaos.py --matrix, tests/test_resil.py, tests/test_spill.py).
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import time
from typing import Callable, NamedTuple, Optional

import jax
import numpy as np
from jax import lax

from ..engine import checkpoint as ckpt
from ..engine.bfs import (
    DEFAULT_FP_HIGHWATER,
    OK,
    VIOL_SLOT_OVERFLOW,
    VIOLATION_NAMES,
    CheckResult,
    carry_done,
    make_engine,
    result_from_carry,
)
from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED
from ..engine.spill import SpillWriteError
from .faults import FaultInjector, FaultPlan, TransientFault
from .regrow import (
    GROWABLE,
    grown,
    migrate_engine_carry,
    migrate_shard_carry,
)

# exception types the segment-retry loop CATCHES; the injected stand-in
# plus whatever XLA runtime error type this jax exposes.  Caught is not
# retried: every caught error is classified first (is_resource_exhausted)
# - a deterministic RESOURCE_EXHAUSTED routes to the degradation ladder,
# only genuinely transient errors get the backoff budget.
_TRANSIENT: tuple = (TransientFault,)
try:  # pragma: no cover - depends on the installed jaxlib
    from jax.errors import JaxRuntimeError

    _TRANSIENT = (TransientFault, JaxRuntimeError)
except ImportError:  # pragma: no cover
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        _TRANSIENT = (TransientFault, XlaRuntimeError)
    except ImportError:
        pass

# python-level allocation failures (and the injected AllocDeniedFault,
# a MemoryError) are caught alongside the runtime errors - they are
# always classified as resource exhaustion, never retried
_CAUGHT: tuple = _TRANSIENT + (MemoryError,)

# XLA status markers of a deterministic allocation failure.  Retrying
# these with backoff burned the full retry budget before dying (the
# PR 2 overreach); the ladder absorbs them instead.
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Resource exhausted",
                "Out of memory", "Allocation failure")


def is_resource_exhausted(e: BaseException) -> bool:
    """Classify a caught runtime error: True for deterministic
    device/host allocation failures (route to the degradation ladder),
    False for the transient class (retry with backoff).  XLA surfaces
    its status code in the message, so classification is by
    status-string; MemoryError (python hosts + the injected
    AllocDeniedFault) is always exhaustion."""
    if isinstance(e, MemoryError):
        return True
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


# CLI exit code for an interrupted-but-checkpointed run (EX_TEMPFAIL:
# "try again later" - distinct from 0/12/13 so schedulers can requeue).
# Capacity exhaustion that survives to a checkpoint (ladder rung 4)
# exits with the same code: both mean "resume me".
EXIT_INTERRUPTED = 75

# chunk-shrink floor of the ladder's rung 3 (below this the fixed
# per-step overheads dominate and halving frees almost nothing)
MIN_CHUNK = 64


class SlotOverflowError(RuntimeError):
    """Codec slot overflow: a state field exceeded its compiled bit
    width.  Not survivable by regrow - the codec/kernel must be rebuilt
    with wider ModelConfig bounds - so the supervisor checkpoints the
    last good carry and raises this with the resume story attached."""

    def __init__(self, ckpt_path: Optional[str]):
        self.ckpt_path = ckpt_path
        hint = (
            f"; last good carry checkpointed at {ckpt_path!r} - after "
            "raising the bounds, restart (a recompiled codec changes the "
            "state encoding, so the checkpoint is diagnostic only)"
            if ckpt_path else "; re-run with -checkpoint to keep a snapshot"
        )
        super().__init__(
            "codec slot overflow: raise the ModelConfig bounds and "
            "recompile - auto-grow cannot widen compiled bit fields" + hint
        )


@dataclasses.dataclass
class SupervisorOptions:
    """Knobs of one supervised run (CLI: -auto-grow/-no-auto-grow,
    -max-regrow, -retry, -checkpoint, -checkpointevery, -recover)."""

    auto_grow: bool = True
    max_regrow: int = 8
    retries: int = 2
    backoff_base_s: float = 0.5
    backoff_cap_s: float = 30.0
    ckpt_path: Optional[str] = None
    ckpt_every: int = 256
    keep_generations: int = 2
    resume: bool = False
    faults: Optional[FaultPlan] = None
    # host spill tier policy (CLI -spill/-no-spill): "auto" activates it
    # when an fpset regrow is denied by the allocation probe (or
    # max_regrow is exhausted); "on" prefers it over regrowing at the
    # FIRST fpset saturation; "off" removes the rung from the ladder
    spill: str = "auto"
    # CLI -phase-timing: swap the fused segment dispatch for the
    # host-fenced expand/commit step loop (obs.phases.PhasedRuntime) so
    # every level gets MEASURED phase walls as `phase` journal events.
    # Bit-for-bit results; costs a fence per step (PERF.md round 11).
    # Adapters without a phased build (pipelined, sharded) fall back to
    # the free segment-scope attribution every run gets anyway.
    phase_timing: bool = False
    # initial host-store capacity (auto-grows in host RAM)
    spill_capacity: int = 1 << 15
    # rung-3 floor: chunk never shrinks below this
    min_chunk: int = MIN_CHUNK
    # coverage-saturation signal: once the run has gone this many BFS
    # levels without visiting a NEW coverage site, one `coverage`
    # journal event with saturated=true is emitted (the live "the spec
    # stopped exploring new behavior" cue; only with a coverage plane)
    coverage_sat_levels: int = 8
    # artifact cache (struct.artifacts): read the final fingerprint
    # table back to host on a CLEAN verdict so the reachable-set tier
    # can be derived from it.  Single-device non-spilled runs only -
    # the spill tier's table is partial and the sharded carry is
    # per-device (CAPTURES_FPS on the adapter gates it)
    capture_fps: bool = False
    # programmatic drain request (ISSUE 17): a threading.Event twin of
    # _SignalCatcher for in-process preemption - the serve scheduler
    # sets it to preempt ONE supervised job (deadline / priority /
    # cancel) without signaling the whole server.  Checked at the same
    # segment boundaries as sig.hit, so a drained run rides the
    # identical checkpoint + exit-75 machinery and its -recover resume
    # is bit-for-bit the uninterrupted run
    drain: Optional[object] = None
    # on_event(kind, info_dict): checkpoint / ckpt_write_failed / recovery
    # / regrow / retry / interrupted / progress / spill / degrade /
    # exhausted - the tlc_log banner seam
    on_event: Optional[Callable[[str, dict], None]] = None


class SupervisedResult(NamedTuple):
    result: CheckResult
    params: dict  # final engine geometry (auto-grown values included)
    regrows: int
    retries: int
    interrupted: bool
    segments: int
    ckpt_writes: int
    ckpt_write_s: float  # total seconds spent writing checkpoints
    regrow_s: float  # total seconds spent in regrow migration + rebuild
    # --- degradation-ladder telemetry (defaults keep old callers) -----
    exhausted: bool = False  # rung 4: capacity unrecoverable, resume me
    spilled: int = 0  # fingerprints resident in the host spill store
    spill_flushes: int = 0  # device-table -> host-store migrations
    spill_hits: int = 0  # candidates the host tier vetoed
    shrinks: int = 0  # rung-3 chunk halvings


class _SignalCatcher:
    """Installs SIGTERM/SIGINT handlers that record the signal instead of
    killing the process, so the supervision loop can drain the current
    segment and checkpoint.  Restores previous handlers on exit; degrades
    to a no-op off the main thread (signal.signal raises there)."""

    SIGNUMS = (signal.SIGTERM, signal.SIGINT)

    def __enter__(self):
        self.hit = None
        self._saved = {}
        for s in self.SIGNUMS:
            try:
                self._saved[s] = signal.signal(
                    s, lambda signum, frame: self._record(signum)
                )
            except ValueError:  # not the main thread
                pass
        return self

    def _record(self, signum):
        self.hit = signum

    def __exit__(self, *exc):
        for s, h in self._saved.items():
            signal.signal(s, h)
        return False


class SingleDeviceAdapter:
    """Supervision seam over the single-device segmented engine
    (engine.checkpoint's driver, reshaped so the supervisor owns the
    loop).  Growable params: queue_capacity, fp_capacity.

    `backend` (a SpecBackend) swaps the hand-tuned KubeAPI kernel for
    any frontend's compiled step - struct-compiled specs ride the SAME
    supervision loop, checkpoint format and regrow migration with zero
    frontend-specific recovery code; `meta_config` then replaces the
    ModelConfig stanza in the checkpoint meta."""

    kind = "single"
    # the artifact cache may read this adapter's final fpset table back
    # (one table, whole reachable set; the sharded adapter's carry is
    # per-device and stays uncaptured)
    CAPTURES_FPS = True
    GEOM_KEYS = ("queue_capacity", "fp_capacity")
    FIXED_KEYS = ("format", "config", "chunk", "fp_index", "seed",
                  "fp_highwater", "pipeline", "obs_slots", "coverage",
                  "sort_free", "deferred", "symmetry", "por")

    def __init__(self, cfg, chunk: int = 1024,
                 fp_index: int = DEFAULT_FP_INDEX, seed: int = DEFAULT_SEED,
                 fp_highwater: float = DEFAULT_FP_HIGHWATER,
                 backend=None, meta_config: dict = None,
                 check_deadlock: bool = True, pipeline: bool = False,
                 obs_slots: int = 0, coverage: bool = False,
                 sort_free: bool = None, deferred: bool = None):
        from ..engine.bfs import resolve_deferred, resolve_sort_free

        self.cfg = cfg
        self.chunk = chunk
        # resolved once, against the INITIAL chunk: a later ladder
        # chunk-shrink keeps the mode (the slab is rebuilt from the new
        # stage-pair geometry; meta stays consistent across the resume)
        self.sort_free = resolve_sort_free(sort_free, chunk)
        self.deferred = resolve_deferred(deferred, chunk)
        self.fp_index = fp_index
        self.seed = seed
        self.fp_highwater = fp_highwater
        if backend is None and coverage:
            # the KubeAPI path with the device coverage plane: build
            # the covered backend once so sites/meta/engine agree
            from ..engine.backend import kubeapi_backend

            backend = kubeapi_backend(cfg, coverage=True)
            check_deadlock = True  # the kubeapi backend's own default
        self.backend = backend
        self.meta_config = meta_config
        self.check_deadlock = check_deadlock
        self.pipeline = pipeline
        self.obs_slots = obs_slots
        # the flag that shapes the carry layout (checkpoint meta key):
        # True iff the engine actually carries the coverage leaves
        self.coverage = (backend is not None
                         and backend.coverage is not None)
        # reduction flags ride the backend the same way: a reduced run
        # explores a different (smaller) frontier, so resuming across
        # a flag change must mismatch loudly (checkpoint meta keys)
        red = getattr(backend, "reduce", None)
        self.symmetry = bool(red is not None and red.plan is not None)
        self.por = bool(red is not None and red.por and red.safe_ids)

    def build(self, params: dict, ckpt_every: int):
        # donate=False: the supervisor feeds the SAME last-good carry
        # back into the segment on retry/regrow and checkpoints it while
        # the next segment is in flight - donation would invalidate it
        if self.backend is not None:
            from ..engine.bfs import make_backend_engine

            init_fn, _, step_fn = make_backend_engine(
                self.backend, self.chunk, params["queue_capacity"],
                params["fp_capacity"], self.fp_index, self.seed,
                fp_highwater=self.fp_highwater,
                check_deadlock=self.check_deadlock,
                pipeline=self.pipeline, donate=False,
                obs_slots=self.obs_slots, sort_free=self.sort_free,
                deferred=self.deferred,
            )
        else:
            init_fn, _, step_fn = make_engine(
                self.cfg, self.chunk, params["queue_capacity"],
                params["fp_capacity"], self.fp_index, self.seed,
                fp_highwater=self.fp_highwater,
                pipeline=self.pipeline, donate=False,
                obs_slots=self.obs_slots, sort_free=self.sort_free,
                deferred=self.deferred,
            )

        @jax.jit
        def segment(c):
            return lax.fori_loop(0, ckpt_every, lambda _, cc: step_fn(cc), c)

        template = init_fn()
        compiled = segment.lower(template).compile()
        # async contract: seg_fn DISPATCHES and returns in-flight arrays;
        # the supervision loop overlaps host work (checkpoint write,
        # stats readback of the previous carry) with the running segment
        # and fences with jax.block_until_ready
        return template, compiled

    def meta(self, params: dict) -> dict:
        return ckpt._meta(
            self.cfg, meta_config=self.meta_config, chunk=self.chunk,
            fp_index=self.fp_index, seed=self.seed,
            fp_highwater=self.fp_highwater, pipeline=self.pipeline,
            obs_slots=self.obs_slots, coverage=self.coverage,
            sort_free=self.sort_free, deferred=self.deferred,
            symmetry=self.symmetry, por=self.por,
            **params,
        )

    def viol(self, carry) -> int:
        return int(carry.viol)

    def done(self, carry) -> bool:
        return carry_done(carry)

    def cov_sites(self):
        """The coverage plane's site table (None when coverage is off);
        the supervisor keys its `coverage` journal deltas on it."""
        if self.backend is not None and self.backend.coverage is not None:
            return self.backend.coverage.sites
        return None

    def cov_totals(self, carry):
        from ..engine.bfs import cov_totals

        return cov_totals(carry)

    def obs_rows(self, carry, since: int, params: dict):
        """New observability-ring rows since cursor `since` (journal
        `level` events); ([], since) when obs is off."""
        from ..engine.bfs import obs_rows

        return obs_rows(carry, since=since,
                        fp_capacity=params["fp_capacity"])

    def progress(self, carry):
        # one batched device_get instead of four blocking scalar pulls;
        # a pipelined carry's staged block counts as queued work
        st = carry.st_n if carry.st_n is not None else 0
        d, g, di, ln, qh, nn, sn = jax.device_get(
            (carry.depth, carry.generated, carry.distinct,
             carry.level_n, carry.qhead, carry.next_n, st)
        )
        return (
            int(d), int(g), int(di),
            int(ln) - int(qh) + int(nn) + int(sn),
        )

    def migrate(self, carry, old_params: dict, new_params: dict):
        return migrate_engine_carry(carry, old_params, new_params)

    # ---- degradation-ladder seams (engine.spill / chunk shrink) -------

    def supports_spill(self) -> bool:
        # the spill driver runs the unpipelined fused stages; a
        # pipelined carry's staged block has no spill composition (the
        # ladder degrades those runs to the next rung instead)
        return not self.pipeline

    def build_spill(self, params: dict, store, on_event=None,
                    spill_write_hook=None):
        """A SpillRuntime over this adapter's backend + geometry (the
        supervisor swaps its segment function for the runtime's when
        the ladder activates the host tier)."""
        from ..engine.spill import SpillRuntime

        backend = self.backend
        check_deadlock = self.check_deadlock
        if backend is None:
            from ..engine.backend import kubeapi_backend

            backend = kubeapi_backend(self.cfg)
            check_deadlock = None  # the kubeapi backend's own default
        return SpillRuntime(
            backend, self.chunk, params["queue_capacity"],
            params["fp_capacity"], fp_index=self.fp_index,
            seed=self.seed, fp_highwater=self.fp_highwater,
            check_deadlock=check_deadlock, obs_slots=self.obs_slots,
            sort_free=self.sort_free, deferred=self.deferred,
            store=store, on_event=on_event,
            spill_write_hook=spill_write_hook,
        )

    def supports_phase_timing(self) -> bool:
        # fencing the pipelined body would serialize the overlap it
        # exists to create; the ladder's segment-scope attribution
        # still applies there
        return not self.pipeline

    def build_phased(self, params: dict, ckpt_every: int, recorder):
        """(template, seg_fn) through obs.phases.PhasedRuntime: the
        host-fenced expand/commit step loop with measured per-level
        walls, bit-for-bit the fused segment's carry."""
        from ..obs.phases import PhasedRuntime

        backend = self.backend
        check_deadlock = self.check_deadlock
        if backend is None:
            from ..engine.backend import kubeapi_backend

            backend = kubeapi_backend(self.cfg)
            check_deadlock = None  # the kubeapi backend's own default
        rt = PhasedRuntime(
            backend, self.chunk, params["queue_capacity"],
            params["fp_capacity"], fp_index=self.fp_index,
            seed=self.seed, fp_highwater=self.fp_highwater,
            check_deadlock=check_deadlock, obs_slots=self.obs_slots,
            sort_free=self.sort_free, deferred=self.deferred,
            recorder=recorder,
        )
        return rt.init_fn(), rt.segment_fn(ckpt_every)

    def can_shrink(self, floor: int = MIN_CHUNK) -> bool:
        return not self.pipeline and self.chunk // 2 >= floor

    def reseat_chunk(self, carry, params: dict):
        """Halve the pop width: re-seat the carry's queue padding for
        chunk/2 and record the new width (rung 3 - counts/verdict
        preserved, in-batch attribution caveat in resil.regrow)."""
        new_chunk = self.chunk // 2
        migrated = migrate_engine_carry(
            carry, params, params, new_chunk=new_chunk
        )
        self.chunk = new_chunk
        return migrated

    def result(self, carry, wall: float, segments: int,
               params: dict) -> CheckResult:
        from ..engine.fpset import fpset_actual_collision

        afc = float(fpset_actual_collision(carry.fps))
        kw = {}
        if self.backend is not None:
            kw = dict(labels=self.backend.labels,
                      viol_names=self.backend.viol_names,
                      sites=self.cov_sites())
        return result_from_carry(
            carry, wall, iterations=segments,
            fp_capacity=params["fp_capacity"], **kw,
        )._replace(actual_fp_collision=afc)


class ShardedAdapter:
    """Supervision seam over the mesh-sharded engine.  All capacities are
    PER DEVICE; route_factor regrows without carry migration."""

    kind = "sharded"
    GEOM_KEYS = ("queue_capacity", "fp_capacity", "route_factor")
    FIXED_KEYS = ("format", "config", "devices", "fp_highwater",
                  "pipeline", "obs_slots", "coverage", "sort_free",
                  "deferred", "symmetry", "por")

    def __init__(self, cfg, mesh, chunk: int = 512, backend=None,
                 meta_config: dict = None,
                 fp_highwater: float = DEFAULT_FP_HIGHWATER,
                 pipeline: bool = False, obs_slots: int = 0,
                 coverage: bool = False, sort_free: bool = None,
                 deferred: bool = None):
        from ..engine.bfs import resolve_deferred, resolve_sort_free
        from ..engine.sharded import kubeapi_backend

        self.cfg = cfg
        self.mesh = mesh
        self.chunk = chunk
        self.sort_free = resolve_sort_free(sort_free, chunk)
        self.deferred = resolve_deferred(deferred, chunk)
        self.backend = (backend if backend is not None
                        else kubeapi_backend(cfg, coverage=coverage))
        self.meta_config = meta_config
        self.fp_highwater = fp_highwater
        self.pipeline = pipeline
        self.obs_slots = obs_slots
        self.coverage = self.backend.coverage is not None
        red = getattr(self.backend, "reduce", None)
        self.symmetry = bool(red is not None and red.plan is not None)
        self.por = bool(red is not None and red.por and red.safe_ids)

    def build(self, params: dict, ckpt_every: int):
        from ..engine.sharded import make_sharded_engine

        init_fn, seg_fn = make_sharded_engine(
            self.cfg, self.mesh, self.chunk,
            params["queue_capacity"], params["fp_capacity"],
            route_factor=params["route_factor"], segment=ckpt_every,
            backend=self.backend, fp_highwater=self.fp_highwater,
            pipeline=self.pipeline, obs_slots=self.obs_slots,
            sort_free=self.sort_free, deferred=self.deferred,
        )
        template = init_fn()
        compiled = seg_fn.lower(template).compile()
        # async contract: dispatch only; the supervision loop fences
        return template, compiled

    def meta(self, params: dict) -> dict:
        return ckpt._meta(
            self.cfg, meta_config=self.meta_config, chunk=self.chunk,
            devices=int(self.mesh.devices.size),
            fp_highwater=self.fp_highwater, pipeline=self.pipeline,
            obs_slots=self.obs_slots, coverage=self.coverage,
            sort_free=self.sort_free, deferred=self.deferred,
            symmetry=self.symmetry, por=self.por,
            **params,
        )

    def cov_sites(self):
        if self.backend.coverage is not None:
            return self.backend.coverage.sites
        return None

    def cov_totals(self, carry):
        from ..engine.bfs import cov_totals

        return cov_totals(carry)

    def viol(self, carry) -> int:
        return int(np.asarray(carry.viol).max())

    def done(self, carry) -> bool:
        return not bool(np.asarray(carry.cont).any())

    def progress(self, carry):
        # one batched device_get instead of five blocking pulls
        d, g, di, qt, qh = jax.device_get(
            (carry.depth, carry.generated, carry.distinct,
             carry.qtail, carry.qhead)
        )
        return (
            int(np.asarray(d).max()),
            int(np.asarray(g).sum()),
            int(np.asarray(di).sum()),
            int((np.asarray(qt) - np.asarray(qh)).sum()),
        )

    def obs_rows(self, carry, since: int, params: dict):
        from ..engine.sharded import obs_rows_sharded

        return obs_rows_sharded(
            carry, since=since,
            fp_capacity_total=(params["fp_capacity"]
                               * int(self.mesh.devices.size)),
        )

    def supports_spill(self) -> bool:
        from ..engine.sharded import SPILL_CAPABLE

        # like the single-device adapter: the spill driver runs the
        # unpipelined halves; a pipelined carry's pending-verdict block
        # has no spill composition (ladder degrades to the next rung)
        return SPILL_CAPABLE and not self.pipeline

    def build_spill(self, params: dict, store, on_event=None,
                    spill_write_hook=None):
        """A ShardedSpillRuntime over this adapter's backend + geometry
        (the supervisor swaps its segment function for the runtime's
        when the ladder activates the host tier on a sharded run)."""
        from ..engine.sharded import ShardedSpillRuntime

        return ShardedSpillRuntime(
            self.cfg, self.mesh, self.chunk,
            params["queue_capacity"], params["fp_capacity"],
            route_factor=params["route_factor"], backend=self.backend,
            fp_highwater=self.fp_highwater, obs_slots=self.obs_slots,
            sort_free=self.sort_free, deferred=self.deferred,
            store=store, on_event=on_event,
            spill_write_hook=spill_write_hook,
        )

    def migrate(self, carry, old_params: dict, new_params: dict):
        return migrate_shard_carry(carry, old_params, new_params)

    def result(self, carry, wall: float, segments: int,
               params: dict) -> CheckResult:
        from ..engine.sharded import result_from_shard_carry

        return result_from_shard_carry(
            carry, wall, iterations=segments,
            labels=self.backend.labels,
            viol_names=self.backend.viol_names,
            fp_capacity_total=(
                params["fp_capacity"] * int(self.mesh.devices.size)
            ),
            sites=self.cov_sites(),
        )


def _params_from_meta(adapter, meta: dict, params: dict) -> dict:
    """Resume geometry resolution: fixed keys (config, codec-shaping
    parameters) must match what this process would write; growable
    geometry keys are TAKEN FROM THE CHECKPOINT (auto-grown capacities
    travel with the snapshot, so the resume command needs none of them)."""
    want = adapter.meta(params)
    for key in adapter.FIXED_KEYS:
        # pre-pipeline/pre-obs/pre-coverage/pre-sort-free/pre-
        # deferred/pre-reduction snapshots carry no key: they were cut
        # from engines without those features, so missing means off
        have = meta.get(key, False if key in ("pipeline", "coverage",
                                              "sort_free", "deferred",
                                              "symmetry", "por")
                        else 0 if key == "obs_slots" else None)
        if have != want.get(key):
            raise ValueError(
                f"checkpoint {key} mismatch: "
                f"{have!r} != {want.get(key)!r}"
            )
    out = dict(params)
    for key in adapter.GEOM_KEYS:
        if key in meta:
            out[key] = meta[key]
    return out


def _emit(opts: SupervisorOptions, kind: str, **info) -> None:
    if opts.on_event is not None:
        opts.on_event(kind, info)


def _resume(adapter, params: dict, opts: SupervisorOptions,
            make_spill_runtime, build=None):
    """Load the newest verifiable checkpoint of the family `ckpt_path`
    (generations first, then the plain file for pre-supervisor
    snapshots), rebuilding the engine with the recorded geometry.  A
    checkpoint whose meta records an active spill tier restores the
    paired host-store file too (engine.spill.spill_sibling) - a torn
    or missing sibling fails the WHOLE generation, falling back to the
    previous intact pair, so the two tiers can never resume skewed.
    Returns (params, template, seg_fn, carry, path, spill_rt)."""
    from ..engine.spill import SpillStore, spill_sibling

    base = opts.ckpt_path
    cands = [p for _, p in reversed(ckpt.list_generations(base))]
    if os.path.exists(base):
        cands.append(base)
    if not cands:
        raise FileNotFoundError(f"no checkpoint at {base!r}")
    last_err = None
    for path in cands:
        try:
            meta = ckpt.read_checkpoint_meta(path)
        except ckpt.CheckpointCorruptError as e:
            last_err = e
            _emit(opts, "ckpt_fallback", path=path, error=str(e))
            continue
        new_params = _params_from_meta(adapter, meta, params)
        spill_rt = None
        if (meta.get("spill") or {}).get("active"):
            try:
                store = SpillStore.load(spill_sibling(path))
            except (ckpt.CheckpointCorruptError, OSError,
                    FileNotFoundError, KeyError) as e:
                last_err = e
                _emit(opts, "ckpt_fallback", path=path,
                      error=f"spill sibling: {e}")
                continue
            spill_rt = make_spill_runtime(new_params, store)
            template = spill_rt.init_fn()
            seg_fn = spill_rt.segment_fn(opts.ckpt_every)
        else:
            template, seg_fn = (
                build(new_params) if build is not None
                else adapter.build(new_params, opts.ckpt_every)
            )
        try:
            _, carry = ckpt.load_checkpoint(path, template)
        except ckpt.CheckpointCorruptError as e:
            last_err = e
            _emit(opts, "ckpt_fallback", path=path, error=str(e))
            continue
        return new_params, template, seg_fn, carry, path, spill_rt
    raise FileNotFoundError(
        f"no intact checkpoint under {base!r} (newest failure: {last_err})"
    )


def _probe_grow(resource: str, new_value, faults) -> Optional[str]:
    """The regrow allocation probe: confirm the DOUBLED resource is
    allocatable before tearing into a carry migration (a denied
    allocation used to crash mid-regrow - the exact moment the run
    mattered most).  Returns None when allocatable, else the denial
    reason.  Sized per resource (bytes of the new container, the
    dominant term; route_factor buckets are too small to probe)."""
    import jax
    import jax.numpy as jnp

    nbytes = {
        "fp_capacity": 8,  # 2 uint32 words per slot
        "queue_capacity": 64,  # 2 buffers x packed words, upper bound
        "route_factor": 0,
    }.get(resource, 8) * int(new_value if resource != "route_factor"
                             else 0)
    try:
        faults.alloc_probe()
        if nbytes > 0:
            buf = jnp.zeros(nbytes, jnp.uint8)
            jax.block_until_ready(buf)
            del buf
        return None
    except Exception as e:  # noqa: BLE001 - classified right below
        if is_resource_exhausted(e):
            return str(e)
        raise


def _supports_spill(adapter) -> bool:
    f = getattr(adapter, "supports_spill", None)
    return bool(f()) if callable(f) else False


def _can_shrink(adapter, floor: int) -> bool:
    f = getattr(adapter, "can_shrink", None)
    return bool(f(floor)) if callable(f) else False


def supervise(adapter, params: dict,
              opts: SupervisorOptions = None) -> SupervisedResult:
    """Run an exhaustive check under supervision.  `params` holds the
    adapter's growable geometry (queue_capacity, fp_capacity, and
    route_factor for the sharded adapter); everything else is fixed in
    the adapter.  Returns the final CheckResult plus recovery telemetry.

    Capacity exhaustion walks the degradation ladder (module
    docstring): probed regrow -> host spill tier -> chunk shrink ->
    checkpoint + exhausted=True.  When the spill tier is active the
    supervisor keeps a host-store SNAPSHOT paired with every last-good
    carry, so retry/regrow replays roll both tiers back in lock-step
    (a store that ran ahead of a rolled-back carry would veto states
    the carry has not counted yet - a silent undercount)."""
    opts = opts or SupervisorOptions()
    faults = FaultInjector(opts.faults)
    rng = random.Random(0xC0FFEE)  # deterministic backoff jitter
    params = dict(params)
    regrows = retries_used = segments = ckpt_writes = shrinks = 0
    ckpt_write_s = regrow_s = 0.0
    interrupted = exhausted = False
    exhaust_resource = ""
    spill_rt = None  # engine.spill.SpillRuntime once the tier is active
    good_store = None  # SpillStoreSnapshot paired with `good`

    def emit_info(kind, info):
        _emit(opts, kind, **info)

    def make_spill_runtime(p, store):
        return adapter.build_spill(
            p, store, on_event=emit_info,
            spill_write_hook=faults.spill_write,
        )

    # -phase-timing: measured per-level expand/commit walls through the
    # host-fenced step loop, where the adapter supports it (unpipelined
    # single-device); every run gets the free segment-scope attribution
    # below regardless
    phase_rec = None
    if (opts.phase_timing
            and callable(getattr(adapter, "build_phased", None))
            and getattr(adapter, "supports_phase_timing",
                        lambda: False)()):
        from ..obs.phases import PhaseRecorder

        phase_rec = PhaseRecorder()

    def build_engine(p):
        if phase_rec is not None:
            return adapter.build_phased(p, opts.ckpt_every, phase_rec)
        return adapter.build(p, opts.ckpt_every)

    def rebuild(p):
        """(template, seg_fn) for geometry `p` in the CURRENT mode: the
        spill runtime is rebuilt around the same host store when the
        tier is active (queue regrow / chunk shrink under spill)."""
        nonlocal spill_rt
        if spill_rt is not None:
            old = spill_rt
            spill_rt = make_spill_runtime(p, old.store)
            spill_rt.flushes = old.flushes
            spill_rt.probes = old.probes
            return spill_rt.init_fn(), spill_rt.segment_fn(opts.ckpt_every)
        return build_engine(p)

    if opts.resume:
        if not opts.ckpt_path:
            raise ValueError("resume requires a checkpoint path")
        params, template, seg_fn, carry, path, spill_rt = _resume(
            adapter, params, opts, make_spill_runtime,
            build=build_engine,
        )
        prog = adapter.progress(carry)
        _emit(opts, "recovery", path=path, depth=prog[0],
              generated=prog[1], distinct=prog[2], queue=prog[3])
    else:
        template, seg_fn = build_engine(params)
        carry = template
    # timer starts after the (AOT) build, matching bfs.check's discipline
    # (regrow rebuilds DO count: recompilation is part of regrow's price)
    t0 = time.time()

    def save(carry_to_save, label: str, store_snap=None):
        nonlocal ckpt_writes, ckpt_write_s
        if not opts.ckpt_path:
            return None
        faults.before_write()
        t = time.time()
        meta = adapter.meta(params)
        if spill_rt is not None and store_snap is not None:
            # the host tier travels as a CRC'd sibling file; meta
            # records it so -recover knows to restore BOTH tiers
            meta["spill"] = {
                "active": True, "count": int(store_snap.count),
                "capacity": int(store_snap.table.shape[0]),
            }
        path = ckpt.save_generation(
            opts.ckpt_path, carry_to_save, meta,
            keep=opts.keep_generations,
        )
        if spill_rt is not None and store_snap is not None:
            from ..engine.spill import save_snapshot, spill_sibling

            save_snapshot(spill_sibling(path), store_snap)
        # refresh the plain family head too (hardlink, no data copy):
        # non-supervised tooling and the TLC `-recover` muscle memory
        # expect the checkpoint to exist under the path the user gave
        heads = [(path, opts.ckpt_path)]
        if spill_rt is not None and store_snap is not None:
            heads.append((path + ".spill", opts.ckpt_path + ".spill"))
        for src_path, head in heads:
            tmp = head + ".head.tmp"
            try:
                os.link(src_path, tmp)
                os.replace(tmp, head)
            except OSError:
                try:
                    import shutil

                    shutil.copyfile(src_path, tmp)
                    os.replace(tmp, head)
                except OSError:
                    pass
        ckpt_write_s += time.time() - t
        ckpt_writes += 1
        faults.after_write(path)
        _emit(opts, "checkpoint", path=path,
              seconds=round(time.time() - t, 3), label=label)
        return path

    good = carry
    if spill_rt is not None:
        good_store = spill_rt.store.snapshot()
    # observability cursor: ring rows below this head are already
    # journaled.  A resumed carry starts past its restored history (the
    # original journal already holds those levels); regrow/retry replays
    # re-derive rows below the cursor bit-for-bit, so nothing duplicates.
    obs_read = getattr(adapter, "obs_rows", None)
    obs_seen = 0
    if obs_read is not None:
        _, obs_seen = obs_read(carry, 0, params)
    # coverage cursor: per-site totals already journaled.  A resumed
    # carry's restored totals are in the original journal, so they seed
    # the cursor; a fresh run's first event carries the Init visits.
    cov_sites = None
    if callable(getattr(adapter, "cov_sites", None)):
        cov_sites = adapter.cov_sites()
    cov_seen = None
    cov_visited = 0
    cov_level = 0
    cov_last_new_level = 0
    cov_saturated = False
    if cov_sites is not None and opts.resume:
        cov_seen = adapter.cov_totals(carry)
        cov_visited = int((cov_seen > 0).sum())
    # deferred periodic checkpoint: written while the NEXT segment is in
    # flight, so snapshot serialization/fsync overlaps device execution
    # instead of stalling the step loop (the carry is safe to read
    # concurrently because the engines are built donate=False here).
    # In spill mode the pair (carry, host-store snapshot) is deferred
    # TOGETHER so the two tiers can never publish skewed.
    pending_save = None

    def flush_save():
        nonlocal pending_save
        if pending_save is None:
            return
        c, snap = pending_save
        pending_save = None
        try:
            save(c, "periodic", store_snap=snap)
        except OSError as e:
            # a failed snapshot write must not kill a healthy run; the
            # next segment boundary retries
            _emit(opts, "ckpt_write_failed", error=str(e))

    def rollback_store():
        """Roll the host tier back to the last-good boundary: a failed
        or violated segment may have flushed device entries into the
        store, and a store ahead of the carry silently undercounts."""
        if spill_rt is not None and good_store is not None:
            spill_rt.store.restore(good_store)

    drained = (lambda: opts.drain is not None and opts.drain.is_set())
    with _SignalCatcher() as sig:
        while not adapter.done(carry):
            if sig.hit is not None or drained():
                interrupted = True
                break

            # ---- one segment: classify, then retry only transients ----
            attempt = 0
            oom = None
            spill_broken = None
            while True:
                try:
                    faults.segment_start(segments)
                    if phase_rec is not None:
                        # a replayed segment re-measures; timings of
                        # the failed attempt must not double-count
                        phase_rec.reset()
                    t_dispatch = time.time()
                    in_flight = seg_fn(good)
                    # host work overlapping the running segment: the
                    # previous segment's checkpoint write + progress line
                    flush_save()
                    carry2 = jax.block_until_ready(in_flight)
                    t_fence = time.time()
                    break
                except SpillWriteError as e:
                    # the host tier cannot absorb the full device table:
                    # retrying cannot help (the table stays full) - the
                    # ladder's final rung takes it
                    spill_broken = e
                    break
                except _CAUGHT as e:
                    if is_resource_exhausted(e):
                        # deterministic RESOURCE_EXHAUSTED: retrying it
                        # burned the whole backoff budget before dying
                        # (the PR 2 overreach) - the ladder absorbs it
                        oom = e
                        break
                    if attempt >= opts.retries:
                        raise
                    delay = min(
                        opts.backoff_cap_s,
                        opts.backoff_base_s * (2 ** attempt),
                    ) * (0.5 + rng.random())
                    _emit(opts, "retry", attempt=attempt + 1,
                          delay_s=round(delay, 3), error=str(e))
                    time.sleep(delay)
                    attempt += 1
                    retries_used += 1
                    if spill_rt is not None:
                        # both tiers roll back together; the on-disk
                        # path below cannot guarantee a tier-consistent
                        # pair mid-retry, so spill retries stay in-memory
                        rollback_store()
                    elif opts.ckpt_path and ckpt.list_generations(
                        opts.ckpt_path
                    ):
                        # restore from the last good on-disk snapshot
                        # when one exists (device state may be gone
                        # after a real device error); otherwise retry
                        # from the in-memory good carry
                        try:
                            _, _, good = ckpt.load_latest_generation(
                                opts.ckpt_path, template
                            )
                        except FileNotFoundError:
                            pass

            if spill_broken is not None:
                # ladder rung 4 via the spill-write-failure edge:
                # checkpoint what we have (the last-good pair is still
                # consistent - the failed flush never touched the
                # store) and hand back a resumable exit
                rollback_store()
                _emit(opts, "degrade", rung="halt", resource="spill",
                      action="checkpoint+exit", reason=str(spill_broken))
                exhausted = interrupted = True
                exhaust_resource = "spill"
                carry = good
                break

            if oom is not None:
                rollback_store()
                can = _can_shrink(adapter, opts.min_chunk)
                _emit(opts, "degrade", rung="oom", resource="segment",
                      action="shrink" if can else "halt",
                      reason=str(oom))
                if can:
                    old_chunk = adapter.chunk
                    good = adapter.reseat_chunk(good, params)
                    shrinks += 1
                    template, seg_fn = rebuild(params)
                    carry = good
                    _emit(opts, "degrade", rung="shrink",
                          resource="chunk",
                          action=f"{old_chunk}->{adapter.chunk}",
                          reason=str(oom))
                    continue
                exhausted = interrupted = True
                exhaust_resource = "segment"
                carry = good
                break

            v = adapter.viol(carry2)
            if v in GROWABLE:
                resource = GROWABLE[v]
                if not opts.auto_grow:
                    carry = carry2  # explicit opt-out: report the halt
                    break
                rollback_store()
                denial = None
                spill_first = (
                    resource == "fp_capacity" and opts.spill == "on"
                    and spill_rt is None and _supports_spill(adapter)
                )
                # ---- rung 1: probed regrow ---------------------------
                if not spill_first:
                    if regrows >= opts.max_regrow:
                        denial = f"max-regrow ({opts.max_regrow}) reached"
                    else:
                        new_params = grown(params, resource)
                        denial = _probe_grow(
                            resource, new_params[resource], faults
                        )
                    if denial is None:
                        t = time.time()
                        # route_factor is an engine-geometry-only knob
                        # for the carry's containers, but a PIPELINED
                        # sharded carry sizes its pending-verdict
                        # buffers by the route bucket width - migrate()
                        # drains + re-seats them (pass-through otherwise)
                        migrated = adapter.migrate(good, params,
                                                   new_params)
                        template, seg_fn = rebuild(new_params)
                        regrow_s += time.time() - t
                        regrows += 1
                        _emit(opts, "regrow", resource=resource,
                              old=params[resource],
                              new=new_params[resource],
                              violation=VIOLATION_NAMES.get(v, str(v)),
                              regrows=regrows,
                              seconds=round(time.time() - t, 3))
                        params = new_params
                        good = migrated
                        carry = migrated
                        continue  # replay inside the new geometry
                    _emit(opts, "degrade", rung="regrow",
                          resource=resource, action="denied",
                          reason=denial)
                # ---- rung 2: host spill tier (fpset only) ------------
                if (resource == "fp_capacity" and opts.spill != "off"
                        and spill_rt is None
                        and _supports_spill(adapter)):
                    from ..engine.spill import SpillStore

                    spill_rt = make_spill_runtime(
                        params, SpillStore(opts.spill_capacity)
                    )
                    template = spill_rt.init_fn()
                    seg_fn = spill_rt.segment_fn(opts.ckpt_every)
                    good = spill_rt.adopt(good)
                    carry = good
                    good_store = spill_rt.store.snapshot()
                    reason = denial or "spill-first policy (-spill)"
                    _emit(opts, "degrade", rung="spill",
                          resource=resource, action="activate",
                          reason=reason)
                    prog = adapter.progress(good)
                    _emit(opts, "spill", phase="activate",
                          resident=prog[2], spilled=0,
                          capacity=spill_rt.store.capacity,
                          hits=0, probes=0)
                    continue  # replay through the two-tier dedup
                # ---- rung 3: chunk shrink, re-probe on recurrence ----
                if _can_shrink(adapter, opts.min_chunk):
                    old_chunk = adapter.chunk
                    good = adapter.reseat_chunk(good, params)
                    shrinks += 1
                    template, seg_fn = rebuild(params)
                    carry = good
                    _emit(opts, "degrade", rung="shrink",
                          resource="chunk",
                          action=f"{old_chunk}->{adapter.chunk}",
                          reason=denial or "capacity ladder")
                    continue  # replay; the regrow probe retries next halt
                # ---- rung 4: checkpoint + exit 75 --------------------
                _emit(opts, "degrade", rung="halt", resource=resource,
                      action="checkpoint+exit",
                      reason=denial or "no ladder rung applicable")
                exhausted = interrupted = True
                exhaust_resource = resource
                carry = good
                break

            if v == VIOL_SLOT_OVERFLOW:
                path = None
                try:
                    path = save(good, "slot-overflow",
                                store_snap=good_store)
                except OSError:
                    pass
                raise SlotOverflowError(path)

            carry = carry2
            good = carry2
            if spill_rt is not None:
                good_store = spill_rt.store.snapshot()
            segments += 1
            # timeline telemetry: the host-observed dispatch -> fence
            # interval of the segment just completed (the trace
            # exporter's device-track slices come from these)
            _emit(opts, "segment", index=segments - 1,
                  t_dispatch=t_dispatch, t_fence=t_fence,
                  wall_s=round(t_fence - t_dispatch, 6))
            if opts.ckpt_path:
                pending_save = (good, good_store)
            t_readback = time.time()
            if adapter.viol(carry) == OK and not adapter.done(carry):
                d, g, di, q = adapter.progress(carry)
                _emit(opts, "progress", depth=d, generated=g,
                      distinct=di, queue=q)
            if obs_read is not None:
                # decode the counter ring's new per-level rows (the
                # same fence the progress readback already paid for)
                rows, obs_seen = obs_read(carry, obs_seen, params)
                for row in rows:
                    _emit(opts, "level", **row)
                if rows:
                    cov_level = max(cov_level, rows[-1]["level"])
            if cov_sites is not None:
                # device coverage readback at the fence already paid:
                # per-site DELTAS journal as one `coverage` event, and
                # a run that stops visiting NEW sites for N levels
                # journals the saturation signal once
                from ..obs.coverage import coverage_delta_event

                totals = adapter.cov_totals(carry)
                payload = coverage_delta_event(cov_sites, totals,
                                               cov_seen)
                if payload is not None:
                    _emit(opts, "coverage", **payload)
                    cov_seen = totals
                    if payload["visited"] > cov_visited:
                        cov_visited = payload["visited"]
                        cov_last_new_level = cov_level
                if (not cov_saturated and cov_visited
                        and cov_level - cov_last_new_level
                        >= opts.coverage_sat_levels):
                    cov_saturated = True
                    _emit(opts, "coverage", visited=cov_visited,
                          sites=len(cov_sites), delta={},
                          saturated=True, level=cov_level)
            # phase attribution (obs.phases): the free fence-scope rows
            # (device wall + the host readback wall just measured) plus
            # the measured per-level expand/commit walls in -phase-
            # timing mode - pure host arithmetic over syncs already paid
            from ..obs.phases import segment_phases

            for row in segment_phases(
                segments - 1, t_fence - t_dispatch,
                readback_s=time.time() - t_readback,
            ):
                _emit(opts, "phase", **row)
            if phase_rec is not None:
                for row in phase_rec.drain():
                    _emit(opts, "phase", **row)

        # the final segment's snapshot has no next segment to hide
        # behind: write it at the fence
        if interrupted:
            pending_save = None  # superseded by the final generation
            path = None
            try:
                path = save(good,
                            "capacity-exhausted" if exhausted
                            else "final",
                            store_snap=good_store)
            except OSError as e:
                _emit(opts, "ckpt_write_failed", error=str(e))
            # the structured record carries the counters and wall time
            # even when NO checkpoint path is configured (path None =
            # progress lost): the journal still ends with an
            # accountable event, never a silent death
            d, g, di, q = adapter.progress(good)
            if exhausted:
                _emit(opts, "exhausted", resource=exhaust_resource,
                      path=path, generated=g, distinct=di, queue=q,
                      wall_s=round(time.time() - t0, 6))
            else:
                _emit(opts, "interrupted",
                      signum=int(sig.hit) if sig.hit else None,
                      path=path, generated=g, distinct=di, queue=q,
                      wall_s=round(time.time() - t0, 6),
                      drained=drained())
        else:
            flush_save()

    wall = time.time() - t0
    result = adapter.result(carry, wall, segments, params)
    # every supervised run ends with exactly one structured final event:
    # verdict + counters + wall, whatever the exit path
    verdict = ("exhausted" if exhausted
               else "interrupted" if interrupted
               else "violation" if result.violation != OK else "ok")
    if (opts.capture_fps and verdict == "ok" and spill_rt is None
            and getattr(adapter, "CAPTURES_FPS", False)
            and getattr(carry, "fps", None) is not None):
        # the artifact cache's reachable-set source: one host copy of
        # the final table, only on a clean non-spilled single-device
        # verdict (a spilled run's device table is partial)
        result = result._replace(
            fp_table=np.asarray(jax.device_get(carry.fps.table))
        )
    _emit(opts, "final", verdict=verdict, generated=result.generated,
          distinct=result.distinct, depth=result.depth,
          queue=result.queue_left, wall_s=round(wall, 6),
          interrupted=interrupted)
    spill_hits = 0
    if spill_rt is not None and getattr(carry, "spill_hits",
                                        None) is not None:
        # scalar on the single-device carry, [D] partials on the
        # sharded carry - sum covers both
        spill_hits = int(np.asarray(carry.spill_hits).sum())
    return SupervisedResult(
        result=result,
        params=params,
        regrows=regrows,
        retries=retries_used,
        interrupted=interrupted,
        segments=segments,
        ckpt_writes=ckpt_writes,
        ckpt_write_s=round(ckpt_write_s, 6),
        regrow_s=round(regrow_s, 6),
        exhausted=exhausted,
        spilled=spill_rt.store.count if spill_rt is not None else 0,
        spill_flushes=spill_rt.flushes if spill_rt is not None else 0,
        spill_hits=spill_hits,
        shrinks=shrinks,
    )


def check_supervised(
    cfg,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    backend=None,
    meta_config: dict = None,
    check_deadlock: bool = True,
    pipeline: bool = False,
    obs_slots: int = 0,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
    opts: SupervisorOptions = None,
) -> SupervisedResult:
    """Supervised single-device exhaustive check (the check_with_
    checkpoints signature, plus self-healing).  `backend`/`meta_config`
    run any SpecBackend (struct-compiled specs included) through the
    same supervision loop; cfg is then ignored.  `coverage` (KubeAPI
    path) compiles the device coverage plane into the engine; a
    backend that already carries a plane turns it on regardless."""
    adapter = SingleDeviceAdapter(
        cfg, chunk=chunk, fp_index=fp_index, seed=seed,
        fp_highwater=fp_highwater, backend=backend,
        meta_config=meta_config, check_deadlock=check_deadlock,
        pipeline=pipeline, obs_slots=obs_slots, coverage=coverage,
        sort_free=sort_free, deferred=deferred,
    )
    return supervise(
        adapter,
        {"queue_capacity": queue_capacity, "fp_capacity": fp_capacity},
        opts,
    )


def check_sharded_supervised(
    cfg,
    mesh,
    chunk: int = 512,
    queue_capacity: int = 1 << 14,
    fp_capacity: int = 1 << 18,
    route_factor: float = 2.0,
    backend=None,
    meta_config: dict = None,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    pipeline: bool = False,
    obs_slots: int = 0,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
    opts: SupervisorOptions = None,
) -> SupervisedResult:
    """Supervised mesh-sharded exhaustive check (capacities PER DEVICE)."""
    adapter = ShardedAdapter(
        cfg, mesh, chunk=chunk, backend=backend, meta_config=meta_config,
        fp_highwater=fp_highwater, pipeline=pipeline,
        obs_slots=obs_slots, coverage=coverage, sort_free=sort_free,
        deferred=deferred,
    )
    return supervise(
        adapter,
        {
            "queue_capacity": queue_capacity,
            "fp_capacity": fp_capacity,
            "route_factor": route_factor,
        },
        opts,
    )
