"""Self-healing runs: supervisor (auto-regrow, preemption-safe exits,
retry with backoff), carry migration across engine geometries, and the
deterministic fault-injection harness that proves every recovery path."""

from .faults import (  # noqa: F401
    AllocDeniedFault,
    FaultInjector,
    FaultPlan,
    TransientFault,
)
from .regrow import GROWABLE, grown  # noqa: F401
from .supervisor import (  # noqa: F401
    EXIT_INTERRUPTED,
    MIN_CHUNK,
    ShardedAdapter,
    SingleDeviceAdapter,
    SlotOverflowError,
    SupervisedResult,
    SupervisorOptions,
    check_sharded_supervised,
    check_supervised,
    is_resource_exhausted,
    supervise,
)
