"""Carry migration between engine geometries - the auto-regrow core.

A capacity halt (VIOL_FPSET_FULL / VIOL_QUEUE_FULL / VIOL_ROUTE_OVERFLOW)
reaches the supervisor as a poisoned carry: the saturating step already
popped a chunk whose successors were discarded, so the post-violation
carry cannot simply continue.  The supervisor therefore always regrows
from the LAST GOOD carry (the segment boundary before the halt): the
functions here rebuild that carry inside the doubled geometry -
re-inserting every stored fingerprint into the larger bucketized table,
re-seating the frontier buffers, preserving every counter bit-for-bit -
and the supervisor replays the segment.  Because a segment is a pure
function of the carry and dedup verdicts are independent of table
geometry (fpset sort-compaction orders candidates by fingerprint, not by
slot), the regrown run's final statistics equal an uninterrupted
correctly-sized run's exactly (tests/test_resil.py pins this).

What is NOT regrowable: VIOL_SLOT_OVERFLOW means the codec's per-field
bit widths are too narrow - a recompile of the codec/kernel, not a carry
migration; the supervisor degrades that to checkpoint + actionable error.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.bfs import (
    VIOL_FPSET_FULL,
    VIOL_QUEUE_FULL,
    VIOL_ROUTE_OVERFLOW,
    EngineCarry,
)
from ..engine.fpset import (
    BUCKET,
    FPSet,
    fpset_insert_sorted,
    fpset_new,
    unmix_host,
)
from ..engine.sharded import ShardCarry

# violation code -> the engine parameter whose doubling clears it
# (route_factor is sharded-only: a pure engine-geometry knob, the carry
# passes through migration unchanged)
GROWABLE = {
    VIOL_FPSET_FULL: "fp_capacity",
    VIOL_QUEUE_FULL: "queue_capacity",
    VIOL_ROUTE_OVERFLOW: "route_factor",
}


def grown(params: Dict, resource: str) -> Dict:
    """The parameter dict with `resource` doubled (capacities stay powers
    of two; route_factor is a float multiplier)."""
    out = dict(params)
    out[resource] = (
        out[resource] * 2.0 if resource == "route_factor"
        else int(out[resource]) * 2
    )
    return out


def migrate_table(old_table: np.ndarray, new_capacity: int,
                  batch: int = 8192) -> FPSet:
    """Re-insert every stored fingerprint into a fresh table of
    `new_capacity` slots.

    Stored words are avalanche-MIXED; they are unmixed host-side
    (fpset.unmix_host) and fed back through the production insert path
    (fpset_insert_sorted), so the new table is exactly what a from-scratch
    run with the larger capacity would have built for the same fingerprint
    set.  Asserts that no entry was lost or duplicated."""
    old_table = np.asarray(old_table)
    lo = old_table[:, 0::2].reshape(-1)
    hi = old_table[:, 1::2].reshape(-1)
    occ = (lo != 0) | (hi != 0)
    lo, hi = lo[occ], hi[occ]
    n = int(lo.shape[0])
    assert n <= new_capacity, "new capacity below current occupancy"
    raw_lo, raw_hi = unmix_host(lo, hi)
    fps = fpset_new(new_capacity)
    inserted = 0
    for off in range(0, n, batch):
        b_lo = raw_lo[off : off + batch]
        b_hi = raw_hi[off : off + batch]
        nb = len(b_lo)
        if nb < batch:
            b_lo = np.pad(b_lo, (0, batch - nb))
            b_hi = np.pad(b_hi, (0, batch - nb))
        mask = np.arange(batch) < nb
        fps, is_new, _, _ = fpset_insert_sorted(
            fps, jnp.asarray(b_lo), jnp.asarray(b_hi), jnp.asarray(mask)
        )
        inserted += int(np.asarray(is_new).sum())
    assert inserted == n, (
        f"fpset migration lost entries: {inserted} != {n}"
    )
    return fps


def migrate_engine_carry(
    carry, old_params: Dict, new_params: Dict, new_chunk: int = None
) -> EngineCarry:
    """Rebuild a single-device EngineCarry inside the new geometry.

    `carry` is a last-good (pre-violation) carry, host- or device-side.
    Counters, level fencing, and the pop cursor are preserved verbatim;
    only the containers are re-seated: the fingerprint table is
    re-bucketized into the larger capacity and the ping-pong level buffers
    are copied into the wider queue (normalized to parity 0).

    `new_chunk` re-seats the queue's chunk padding for a different pop
    width (the degradation ladder's chunk-shrink rung): level contents
    and every counter are unchanged, but the pop BATCHING changes, so
    in-batch duplicate attribution (outdegree min/max, per-action
    distinct splits of same-fingerprint candidates) may differ from a
    clean run at the original chunk - total counts and the verdict do
    not.  Unpipelined carries only (the staged block is chunk-shaped)."""
    chunk = (int(np.asarray(carry.queue).shape[1])
             - int(old_params["queue_capacity"])) // 2
    if new_chunk is not None:
        assert carry.st_n is None, \
            "chunk re-seat supports unpipelined carries only"
        chunk = int(new_chunk)
    W = int(np.asarray(carry.queue).shape[2])
    qcap2 = int(new_params["queue_capacity"])
    old_queue = np.asarray(carry.queue)
    par = int(carry.parity)
    lvl = int(carry.level_n)
    nxt = int(carry.next_n)
    assert lvl <= qcap2 and nxt <= qcap2, "regrown queue still too small"

    queue2 = np.zeros((2, qcap2 + 2 * chunk, W), np.uint32)
    queue2[0, :lvl] = old_queue[par, :lvl]
    queue2[1, :nxt] = old_queue[1 - par, :nxt]

    fp_cap2 = int(new_params["fp_capacity"])
    if fp_cap2 != int(old_params["fp_capacity"]):
        fps2 = migrate_table(np.asarray(carry.fps.table), fp_cap2)
    else:
        fps2 = FPSet(jnp.asarray(np.asarray(carry.fps.table)))
        assert fps2.table.shape[0] * BUCKET == fp_cap2

    # pipelined staged block (expand-stage output awaiting commit):
    # geometry-independent - packed candidate rows + raw fingerprint
    # words travel verbatim; the replayed segment commits them against
    # the regrown table/queue through the normal insert path
    staged = {}
    if carry.st_n is not None:
        staged = {
            f: jnp.asarray(np.asarray(getattr(carry, f)))
            for f in ("st_packed", "st_lo", "st_hi", "st_valid",
                      "st_action", "st_gen", "st_n", "st_viol",
                      "st_viol_state", "st_viol_action")
        }
    # observability ring: telemetry only, its shape depends on neither
    # capacity - travels verbatim so per-level history survives regrow
    if carry.obs_ring is not None:
        staged.update({
            f: jnp.asarray(np.asarray(getattr(carry, f)))
            for f in ("obs_ring", "obs_head", "obs_bodies",
                      "obs_expanded")
        })
    # spill-mode hit counter: scalar telemetry, travels verbatim (the
    # host store itself rolls back through SpillStore.snapshot/restore)
    if getattr(carry, "spill_hits", None) is not None:
        staged["spill_hits"] = jnp.asarray(
            np.asarray(carry.spill_hits), jnp.uint32
        )
    # runtime-certificate leaves: sticky flag + staged block bit travel
    # verbatim (telemetry; a violation already seen must survive regrow)
    if getattr(carry, "cert_viol", None) is not None:
        staged["cert_viol"] = jnp.asarray(
            np.asarray(carry.cert_viol), bool
        )
    if getattr(carry, "st_cert", None) is not None:
        staged["st_cert"] = jnp.asarray(
            np.asarray(carry.st_cert), bool
        )
    # deferred-evaluation staged raw fields (ISSUE 15): chunk-shaped
    # like the rest of the staged block, geometry-independent - travel
    # verbatim (the chunk re-seat path asserts st_n is None above)
    if getattr(carry, "st_flat", None) is not None:
        staged["st_flat"] = jnp.asarray(
            np.asarray(carry.st_flat), jnp.int32
        )
    # device coverage counters: telemetry, shape depends on neither
    # capacity - travel verbatim so per-site history survives regrow
    for f in ("cov_counts", "st_cov"):
        if getattr(carry, f, None) is not None:
            staged[f] = jnp.asarray(
                np.asarray(getattr(carry, f)), jnp.uint32
            )

    return EngineCarry(
        fps=fps2,
        queue=jnp.asarray(queue2),
        parity=jnp.int32(0),
        qhead=jnp.int32(int(carry.qhead)),
        level_n=jnp.int32(lvl),
        next_n=jnp.int32(nxt),
        level=jnp.int32(int(carry.level)),
        depth=jnp.int32(int(carry.depth)),
        generated=jnp.uint32(int(carry.generated)),
        distinct=jnp.uint32(int(carry.distinct)),
        act_gen=jnp.asarray(np.asarray(carry.act_gen), jnp.uint32),
        act_dist=jnp.asarray(np.asarray(carry.act_dist), jnp.uint32),
        outdeg_hist=jnp.asarray(np.asarray(carry.outdeg_hist), jnp.uint32),
        viol=jnp.int32(int(carry.viol)),
        viol_state=jnp.asarray(np.asarray(carry.viol_state), jnp.int32),
        viol_action=jnp.int32(int(carry.viol_action)),
        **staged,
    )


def migrate_shard_carry(
    carry, old_params: Dict, new_params: Dict
) -> ShardCarry:
    """Rebuild a ShardCarry inside the new geometry (every capacity is
    PER DEVICE; fingerprint ownership - hi & (D-1) - is capacity-
    independent, so entries never move between devices).

    The circular per-device frontier is renumbered to qhead=0 when the
    queue grows (positions are pop-order-preserving: entry i of the
    in-flight window lands at slot i).  route_factor growth changes only
    the engine's all_to_all bucket width - the carry passes through,
    except a PIPELINED carry's pending-verdict buffers, which are sized
    by that width: their statistics are drained host-side first and the
    buffers re-seated empty at the new width."""
    D = int(np.asarray(carry.qhead).shape[0])
    if carry.pv_n is not None:
        old_B = int(np.asarray(carry.pv_send).shape[2])
        ncand = int(np.asarray(carry.pv_sown).shape[1])
        L = int(np.asarray(carry.outdeg_hist).shape[1]) - 2
        from ..engine.sharded import drain_pending_host, route_bucket_width

        new_B = route_bucket_width(
            ncand // L, L, D, float(new_params.get("route_factor", 2.0))
        )
        if new_B != old_B:
            carry = drain_pending_host(carry)
            carry = carry._replace(
                pv_send=jnp.zeros((D, D, new_B), jnp.uint8)
            )
    qcap = int(old_params["queue_capacity"])
    qcap2 = int(new_params["queue_capacity"])
    fp_cap = int(old_params["fp_capacity"])
    fp_cap2 = int(new_params["fp_capacity"])

    table = np.asarray(carry.table)
    if fp_cap2 != fp_cap:
        table2 = np.stack(
            [np.asarray(migrate_table(table[d], fp_cap2).table)
             for d in range(D)]
        )
    else:
        table2 = table

    if qcap2 != qcap:
        queue = np.asarray(carry.queue)
        F = queue.shape[2]
        qhead = np.asarray(carry.qhead)
        qtail = np.asarray(carry.qtail)
        level_end = np.asarray(carry.level_end)
        queue2 = np.zeros((D, qcap2 + 1, F), queue.dtype)
        qhead2 = np.zeros(D, np.int32)
        qtail2 = np.zeros(D, np.int32)
        level_end2 = np.zeros(D, np.int32)
        for d in range(D):
            cnt = int(qtail[d] - qhead[d])
            assert cnt <= qcap2, "regrown queue still too small"
            idxs = (int(qhead[d]) + np.arange(cnt)) % qcap
            queue2[d, :cnt] = queue[d][idxs]
            qtail2[d] = cnt
            level_end2[d] = int(level_end[d]) - int(qhead[d])
    else:
        queue2 = np.asarray(carry.queue)
        qhead2 = np.asarray(carry.qhead)
        qtail2 = np.asarray(carry.qtail)
        level_end2 = np.asarray(carry.level_end)

    pv = {}
    if carry.pv_n is not None:
        pv = {
            f: jnp.asarray(np.asarray(getattr(carry, f)))
            for f in ("pv_send", "pv_sown", "pv_pos", "pv_svalid",
                      "pv_order", "pv_faction", "pv_n")
        }
    if carry.obs_ring is not None:
        pv.update({
            f: jnp.asarray(np.asarray(getattr(carry, f)))
            for f in ("obs_ring", "obs_head", "obs_bodies",
                      "obs_expanded")
        })
    if getattr(carry, "obs_pl_flag", None) is not None:
        # pipeline x obs: the deferred level-flip row (level + staged
        # flag) migrates verbatim - geometry-independent scalars
        pv.update({
            f: jnp.asarray(np.asarray(getattr(carry, f)))
            for f in ("obs_pl_level", "obs_pl_flag")
        })
    if getattr(carry, "cov_counts", None) is not None:
        # device coverage partials: telemetry, geometry-independent
        pv["cov_counts"] = jnp.asarray(
            np.asarray(carry.cov_counts), jnp.uint32
        )
    if getattr(carry, "spill_hits", None) is not None:
        # sharded spill-mode hit partials: telemetry, travels verbatim
        # (the host store rolls back via SpillStore.snapshot/restore)
        pv["spill_hits"] = jnp.asarray(
            np.asarray(carry.spill_hits), jnp.uint32
        )
    return ShardCarry(
        table=jnp.asarray(table2),
        queue=jnp.asarray(queue2),
        qhead=jnp.asarray(qhead2, jnp.int32),
        qtail=jnp.asarray(qtail2, jnp.int32),
        level_end=jnp.asarray(level_end2, jnp.int32),
        level=jnp.asarray(np.asarray(carry.level), jnp.int32),
        depth=jnp.asarray(np.asarray(carry.depth), jnp.int32),
        generated=jnp.asarray(np.asarray(carry.generated), jnp.uint32),
        distinct=jnp.asarray(np.asarray(carry.distinct), jnp.uint32),
        act_gen=jnp.asarray(np.asarray(carry.act_gen), jnp.uint32),
        act_dist=jnp.asarray(np.asarray(carry.act_dist), jnp.uint32),
        outdeg_hist=jnp.asarray(np.asarray(carry.outdeg_hist), jnp.uint32),
        viol=jnp.asarray(np.asarray(carry.viol), jnp.int32),
        viol_state=jnp.asarray(np.asarray(carry.viol_state), jnp.int32),
        viol_local=jnp.asarray(np.asarray(carry.viol_local), bool),
        cont=jnp.asarray(np.asarray(carry.cont), bool),
        **pv,
    )
