"""The jax.distributed pod driver (ISSUE 19 tentpole).

One process per host joins a coordinator (`init_pod`), the global "fp"
mesh spans every host's devices (`pod_mesh`), and `run_pod` drives the
UNCHANGED sharded engine body over it - per-host fingerprint-space
shards fall out of the owner mapping hi & (D-1) because the mesh lays
device rows out process-major, and the candidate-routing `all_to_all`
crosses DCN at exactly the level-fence seam the deferred collective
already batches.  What this module adds is the host-side distribution
protocol around that body:

* **Per-host journals**: each process writes its own
  ``{base}.h{pid}.journal.jsonl`` (schema-v1 ``pod`` events carry the
  membership + per-host gauges); obs.serve's /runs registry and
  obs.views.merge_journals fold the siblings into one stream.
* **Per-host checkpoints**: each process snapshots only its OWN mesh
  rows (``{base}.h{pid}`` - table/queue bytes never cross hosts), with
  meta recording num_hosts/host_rows so a resume at the wrong width
  fails loudly instead of silently misassembling the fingerprint space.
* **Preemption consensus**: SIGTERM on ANY host raises a pod-wide vote
  (a tiny jitted `pmax` - membership is not elastic inside a dispatch),
  every host checkpoints its shard at the same segment fence, and every
  process exits EXIT_PREEMPTED (75, the supervisor's checkpoint+exit
  convention).
* **Reshard-on-recover**: `reshard_carry` re-partitions a saved pod's
  table fingerprints (unmix -> re-insert, the regrow migration idiom)
  and frontier states (re-fingerprint -> re-route) by the new owner
  mapping hi & (D'-1), so a preempted 4-host run resumes as a 2-host
  run with identical semantics (`--reshard`).
* **Per-host spill lifeboat**: ``spill="on"`` swaps the fused segment
  for ShardedSpillRuntime's expand/probe/commit protocol - one
  SpillStore per process, exact because fingerprint spaces are disjoint
  per device (engine/sharded.py).  Spill + reshard is unsupported (the
  host stores are keyed per-host); resume at the original width.

* **Pod-native observability** (ISSUE 20, closing ROADMAP #1 residue
  (a)): ``obs_slots``/``coverage`` thread the PR 5 counter ring and the
  PR 11 CoveragePlane through the sharded engine, so each host's carry
  holds its own ring + ``cov_counts`` rows (checkpointed with the shard,
  migrated on ``--reshard``).  At every segment fence the driver decodes
  only its ADDRESSABLE ring rows into per-host PARTIAL ``level`` events
  and its local ``cov_counts`` rows into per-host ``coverage`` deltas -
  each tagged with a ``host`` field - plus a ``segment`` timing event,
  so obs.views.fold_pod_levels / obs.coverage can re-sum the sibling
  journals into pod-global counters and obs.trace can render one
  timeline with a process row per host, lanes aligned on the fence
  timestamps.  Pure telemetry: obs-on pod runs are bit-for-bit obs-off
  runs (bench.py --pod-obs-ab gates signature + fpset TABLE words).
"""

from __future__ import annotations

import json
import os
import re
import signal
import time
import zlib
from types import SimpleNamespace
from typing import NamedTuple, Optional

import numpy as np

from .. import __version__
from ..config import ModelConfig

EXIT_OK = 0
EXIT_VIOLATION = 12  # TLC ExitStatus safety-violation (cli contract)
EXIT_PREEMPTED = 75  # EX_TEMPFAIL: shard checkpointed, relaunch to resume

DEFAULT_COORDINATOR = "127.0.0.1:12731"

# levels with no new site before the once-per-run saturation event
# fires (the supervisor's coverage_sat_levels default, PR 11)
COVERAGE_SAT_LEVELS = 8

# engine keys a pod resume must always match (mirrors
# check_sharded_with_checkpoints; "spill" shapes the carry leaves)
_ENGINE_KEYS = ("format", "config", "pipeline", "obs_slots", "sort_free",
                "deferred", "symmetry", "por", "spill")
# geometry keys only a --reshard resume may change
_GEOM_KEYS = ("queue_capacity", "fp_capacity", "devices", "num_hosts")

_STAT_FIELDS = ("generated", "distinct", "depth", "qhead", "qtail",
                "level", "cont", "viol", "viol_state", "viol_local",
                "act_gen", "act_dist", "outdeg_hist", "spill_hits",
                "cov_counts")


# ---------------------------------------------------------------------------
# pod bring-up
# ---------------------------------------------------------------------------


def init_pod(coordinator_address: str = DEFAULT_COORDINATOR,
             num_processes: int = 1, process_id: int = 0) -> None:
    """Join the pod BEFORE any other jax call.  On CPU pods the gloo
    collectives backend must be selected before jax.distributed
    initializes (the localhost test topology; TPU pods autodetect and
    skip both knobs when num_processes comes from the runtime)."""
    import jax

    if num_processes <= 1:
        return
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def pod_mesh(devices: int = None):
    """The global single-axis "fp" mesh over EVERY pod device, in the
    process-major order jax.devices() reports - so the owner partition
    hi & (D-1) assigns each host a contiguous row block.  `devices`
    truncates to the first N devices (single-process width-change
    tests; a real pod always meshes every device)."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:devices] if devices else jax.devices())
    assert devs.size & (devs.size - 1) == 0, (
        "pod device count must be a power of two "
        f"(got {devs.size}: set --xla_force_host_platform_device_count "
        "or adjust the host count)"
    )
    return Mesh(devs, ("fp",))


def host_checkpoint_path(base: str, host: int) -> str:
    return f"{base}.h{host}"


def host_journal_path(base: str, host: int) -> str:
    return f"{base}.h{host}.journal.jsonl"


class _SigtermFlag:
    """SIGTERM -> cooperative stop flag, checked at segment fences (the
    dispatch in flight always completes; membership is not elastic
    inside a collective)."""

    def __init__(self):
        self.hit = False
        self._prev = None

    def _handler(self, signum, frame):
        self.hit = True

    def install(self):
        try:
            self._prev = signal.signal(signal.SIGTERM, self._handler)
        except ValueError:  # not the main thread (serve workers)
            self._prev = None

    def uninstall(self):
        if self._prev is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev)
            except ValueError:
                pass


# ---------------------------------------------------------------------------
# collective helpers (tiny jitted shard_maps over the pod mesh)
# ---------------------------------------------------------------------------


def _first_row(arr):
    """Any addressable row of a [D, ...]-sharded array (for leaves the
    engine keeps replicated across the axis: cont/viol/level)."""
    from ..engine.sharded import shard_host_rows

    rows = shard_host_rows(arr)
    return rows[min(rows)]


def _host_value_array(mesh, value: int):
    """[D] int32 global array where THIS process's rows carry `value`
    (each host votes through its own mesh rows)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    D = int(mesh.devices.size)
    (axis,) = mesh.axis_names
    v = np.int32(value)

    def cb(idx):
        s = idx[0]
        stop = s.stop if s.stop is not None else D
        return np.full(stop - (s.start or 0), v, np.int32)

    return jax.make_array_from_callback(
        (D,), NamedSharding(mesh, P(axis)), cb
    )


def make_stop_vote(mesh):
    """Pod-wide preemption consensus: pmax over per-host stop flags, so
    one SIGTERM stops every host at the SAME segment fence."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..engine.sharded import shard_map

    (axis,) = mesh.axis_names
    fn = jax.jit(shard_map(
        lambda flag: lax.pmax(flag[0], axis)[None],
        mesh=mesh, in_specs=(P(axis),), out_specs=P(axis),
        check_vma=False,
    ))

    def vote(local_hit: bool) -> bool:
        if jax.process_count() == 1:
            return bool(local_hit)
        out = fn(_host_value_array(mesh, 1 if local_hit else 0))
        return bool(int(np.asarray(_first_row(out))))

    return vote


def make_stats_gather(mesh, carry):
    """Host access to the FULL [D, ...] statistic leaves on every
    process (all_gather over the mesh; table/queue stay sharded - only
    the O(D) counter rows cross DCN).  The gathered namespace feeds
    result_from_shard_carry unchanged, so pod statistics reduce with
    bit-identical semantics to the single-process path."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..engine.sharded import shard_host_rows, shard_map

    (axis,) = mesh.axis_names
    fields = [f for f in _STAT_FIELDS
              if getattr(carry, f, None) is not None]
    fn = jax.jit(shard_map(
        lambda *xs: tuple(lax.all_gather(x[0], axis)[None] for x in xs),
        mesh=mesh,
        in_specs=tuple(P(axis) for _ in fields),
        out_specs=tuple(P(axis) for _ in fields),
        check_vma=False,
    ))

    def gather(c) -> SimpleNamespace:
        if jax.process_count() == 1:
            return SimpleNamespace(
                **{f: np.asarray(getattr(c, f)) for f in fields}
            )
        outs = fn(*[getattr(c, f) for f in fields])
        vals = {}
        for f, o in zip(fields, outs):
            rows = shard_host_rows(o)
            vals[f] = np.asarray(rows[min(rows)])
        return SimpleNamespace(**vals)

    return gather


# ---------------------------------------------------------------------------
# per-host shard checkpoints
# ---------------------------------------------------------------------------


def save_pod_checkpoint(base: str, carry, meta: dict, host: int) -> str:
    """Snapshot THIS host's mesh rows to ``{base}.h{host}`` (the
    checkpoint.save_checkpoint format: CRC-manifested npz + json meta).
    Meta records num_hosts / host_rows / pod_fields so resume validates
    the partition before touching a single leaf."""
    from ..engine.checkpoint import save_checkpoint
    from ..engine.sharded import shard_host_rows

    rows = {f: shard_host_rows(getattr(carry, f))
            for f in carry._fields if getattr(carry, f) is not None}
    ids = sorted(rows["table"])
    payload = {f: np.stack([r[i] for i in ids]) for f, r in rows.items()}
    # tree_leaves flattens the dict in sorted-key order; pin that order
    # in meta so the shard loader can name leaves without a template
    m = dict(meta, host=host, host_rows=[int(i) for i in ids],
             pod_fields=sorted(payload))
    path = host_checkpoint_path(base, host)
    save_checkpoint(path, payload, m)
    return path


def _load_host_payload(path: str):
    """One shard file -> (meta, {field: [rows, ...] np}), CRC-verified."""
    from ..engine.checkpoint import CheckpointCorruptError

    try:
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            leaves = [z[f"leaf_{i}"] for i in range(
                sum(k.startswith("leaf_") for k in z.files))]
    except Exception as e:
        raise CheckpointCorruptError(f"unreadable pod shard {path!r}: {e}")
    manifest = meta.get("manifest") or {}
    for i, a in enumerate(leaves):
        want = manifest.get(f"leaf_{i}")
        got = zlib.crc32(np.ascontiguousarray(a).tobytes())
        if want is None or got != want:
            raise CheckpointCorruptError(
                f"pod shard {path!r} leaf_{i} CRC mismatch "
                f"({got} != {want}) - torn write or bit rot"
            )
    fields = meta.get("pod_fields")
    if fields is None or len(fields) != len(leaves):
        raise ValueError(
            f"{path!r} is not a pod shard checkpoint (no pod_fields "
            "manifest) - whole-carry snapshots resume through "
            "check_sharded_with_checkpoints instead"
        )
    return meta, dict(zip(fields, leaves))


def _host_paths(base: str):
    """Every ``{base}.h<digits>`` shard file, host-ordered (journal
    siblings excluded by the anchored pattern)."""
    pat = re.compile(re.escape(os.path.basename(base)) + r"\.h(\d+)$")
    d = os.path.dirname(os.path.abspath(base)) or "."
    out = {}
    for name in os.listdir(d):
        m = pat.fullmatch(name)
        if m:
            out[int(m.group(1))] = os.path.join(d, name)
    return [out[k] for k in sorted(out)]


def load_pod_full(base: str):
    """Reassemble the FULL [D_old] host-side carry from every per-host
    shard file (shared filesystem: the localhost pod and NFS-backed TPU
    pods both qualify).  Returns (meta_of_host0, numpy ShardCarry)."""
    from ..engine.sharded import ShardCarry

    paths = _host_paths(base)
    if not paths:
        raise FileNotFoundError(f"no pod checkpoint shards at {base!r}.h*")
    rows: dict = {}
    m0 = None
    for p in paths:
        m, payload = _load_host_payload(p)
        if m0 is None:
            m0 = m
        for f, arr in payload.items():
            for k, rid in enumerate(m["host_rows"]):
                rows.setdefault(f, {})[int(rid)] = arr[k]
    d_old = int(m0["devices"])
    short = sorted(f for f, r in rows.items() if len(r) != d_old)
    if short:
        raise ValueError(
            f"pod checkpoint {base!r} is missing shard rows for {short} "
            f"- a {m0.get('num_hosts')}-host snapshot needs every host's "
            ".h* file on this filesystem"
        )
    full = {f: np.stack([r[i] for i in range(d_old)])
            for f, r in rows.items()}
    return m0, ShardCarry(**{f: full.get(f) for f in ShardCarry._fields})


def _validate_pod_meta(saved: dict, want: dict, reshard: bool) -> None:
    """Loud meta gate before any leaf is touched.  Plain resume pins
    engine AND geometry keys (a snapshot only reloads at its own pod
    width); --reshard relaxes exactly the geometry keys that
    reshard_carry re-derives."""
    defaults = {"pipeline": False, "sort_free": False, "deferred": False,
                "symmetry": False, "por": False, "spill": False,
                "obs_slots": 0, "num_hosts": 1}
    for key in _ENGINE_KEYS + (() if reshard else _GEOM_KEYS):
        s = saved.get(key, defaults.get(key))
        if s != want[key]:
            hint = (
                "; a pod snapshot resumes only at the width that cut it "
                "- relaunch with --reshard to re-partition the "
                "fingerprint space" if key in ("devices", "num_hosts")
                else ""
            )
            raise ValueError(
                f"checkpoint {key} mismatch: {s!r} != {want[key]!r}{hint}"
            )


# ---------------------------------------------------------------------------
# reshard-on-recover
# ---------------------------------------------------------------------------


def reshard_carry(carry, backend, d_new: int,
                  queue_capacity: int = None, fp_capacity: int = None,
                  fp_index: int = None, seed: int = None):
    """Re-partition a full host-side numpy ShardCarry from D_old to
    `d_new` mesh rows under the new owner mapping hi & (d_new - 1).

    Tables: stored words are unmixed back to raw fingerprints (the
    regrow-migration idiom) and re-inserted into the new owner's table,
    so the new stored words are bit-identical to what a fresh run of
    the new width would hold; per-device `distinct` becomes the new
    occupancy (their sum is preserved - verified).  Queues: the live
    window [qhead, qtail) is split at the level boundary, each state is
    re-fingerprinted and routed to its new owner, current-level states
    pack before next-level states, and the head renumbers to 0 (the
    regrow queue-renumber idiom) - so level/depth accounting continues
    exactly.  Scalar replicated leaves copy through; partial counters
    sum into row 0 (owner attribution of PAST counts is bookkeeping
    only - totals are what the result reduces).

    Like the regrow migration, the (0,0)->(1,0) mixed-word remap class
    re-routes by its unmixed preimage, a 2^-64-probability attribution
    quirk with no effect on stored words or counts.
    """
    from ..engine.fingerprint import (
        DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words,
    )
    from ..engine.fpset import host_insert, unmix_host
    from ..engine.sharded import ShardCarry

    import jax.numpy as jnp

    fp_index = DEFAULT_FP_INDEX if fp_index is None else fp_index
    seed = DEFAULT_SEED if seed is None else seed
    if d_new & (d_new - 1):
        raise ValueError(f"pod width must be a power of two, got {d_new}")
    for f in ("pv_n", "spill_hits"):
        if getattr(carry, f, None) is not None:
            raise ValueError(
                f"reshard does not support carries with {f} (pipelined/"
                "spill pod snapshots resume at their own width)"
            )
    table = np.asarray(carry.table)
    queue = np.asarray(carry.queue)
    d_old = table.shape[0]
    F = queue.shape[-1]
    qcap = int(queue_capacity or (queue.shape[1] - 1))
    fpcap = int(fp_capacity or table.shape[1] * 8)

    # fingerprint tables: unmix -> re-insert by the new owner bits
    table2 = np.zeros((d_new, fpcap // 8, 16), np.uint32)
    distinct2 = np.zeros(d_new, np.uint32)
    for d in range(d_old):
        lo = table[d][:, 0::2].reshape(-1)
        hi = table[d][:, 1::2].reshape(-1)
        occ = (lo != 0) | (hi != 0)
        raw_lo, raw_hi = unmix_host(lo[occ], hi[occ])
        for rl, rh in zip(raw_lo.tolist(), raw_hi.tolist()):
            nd = int(rh) & (d_new - 1)
            if host_insert(table2[nd], int(rl), int(rh)):
                distinct2[nd] += 1
    total = int(np.asarray(carry.distinct, np.int64).sum())
    if int(distinct2.sum()) != total:
        raise ValueError(
            f"reshard integrity: re-inserted {int(distinct2.sum())} "
            f"fingerprints but the snapshot holds {total} distinct - "
            "corrupt shard or fp_capacity too small for the new width"
        )

    # frontier queues: split the live window at the level boundary,
    # route each state to its new fingerprint owner, head renumbers to 0
    qhead = np.asarray(carry.qhead)
    qtail = np.asarray(carry.qtail)
    lend = np.asarray(carry.level_end)
    cur_rows, nxt_rows = [], []
    for d in range(d_old):
        qh, qt, le = int(qhead[d]), int(qtail[d]), int(lend[d])
        live = queue[d, qh:qt]
        ncur = max(0, min(le, qt) - qh)
        cur_rows.append(live[:ncur])
        nxt_rows.append(live[ncur:])

    def owners(states):
        if len(states) == 0:
            return np.zeros(0, np.int64)
        packed = backend.cdc.pack(jnp.asarray(states))
        _lo, hi = fp64_words(packed, backend.cdc.nbits, fp_index, seed)
        return np.asarray(hi).astype(np.int64) & (d_new - 1)

    queue2 = np.zeros((d_new, qcap + 1, F), np.int32)
    qtail2 = np.zeros(d_new, np.int32)
    lend2 = np.zeros(d_new, np.int32)
    for phase, chunks in (("cur", cur_rows), ("nxt", nxt_rows)):
        states = (np.concatenate(chunks) if chunks
                  else np.zeros((0, F), np.int32))
        own = owners(states)
        for d in range(d_new):
            sel = states[own == d]
            n = len(sel)
            if int(qtail2[d]) + n > qcap:
                raise ValueError(
                    f"resharded frontier does not fit: new device {d} "
                    f"needs {int(qtail2[d]) + n} queue rows > "
                    f"queue_capacity {qcap} - rerun with a larger "
                    "--queue-capacity (reshard re-derives geometry)"
                )
            queue2[d, qtail2[d]:qtail2[d] + n] = sel
            qtail2[d] += n
        if phase == "cur":
            lend2 = qtail2.copy()

    def row0(x):
        x = np.asarray(x)
        out = np.zeros((d_new,) + x.shape[1:], x.dtype)
        out[0] = x.sum(axis=0)
        return out

    def repl(x):
        x = np.asarray(x)
        return np.full((d_new,) + x.shape[1:], x[0], x.dtype)

    vs2 = np.zeros((d_new, F), np.int32)
    vl2 = np.zeros(d_new, bool)
    vl = np.asarray(carry.viol_local)
    if vl.any():
        vs2[0] = np.asarray(carry.viol_state)[int(np.argmax(vl))]
        vl2[0] = True

    extra = {}
    if getattr(carry, "cov_counts", None) is not None:
        extra["cov_counts"] = row0(carry.cov_counts)
    if getattr(carry, "obs_ring", None) is not None:
        # the ring's per-level rows are attributions of PAST partials -
        # like the row-0 counters above they are bookkeeping, not state;
        # the new width starts a fresh ring.  Only the STICKY flags must
        # survive: sticky_overflow reads the max over the WHOLE ring
        # (dump row included), so writing the old pod's flag maxima
        # into every new dump row keeps overflow/cert/sym sticky across
        # the reshard.  Heads replicate the old minimum so the resumed
        # driver's decode cursor (restored local min head) sees no
        # phantom rows in the zeroed region.
        from ..obs.counters import COL_CERT, COL_OVERFLOW, COL_SYM

        ring = np.asarray(carry.obs_ring)
        ring2 = np.zeros((d_new,) + ring.shape[1:], ring.dtype)
        for col in (COL_OVERFLOW, COL_CERT, COL_SYM):
            ring2[:, -1, col] = ring[:, :, col].max()
        heads = np.asarray(carry.obs_head)
        extra["obs_ring"] = ring2
        extra["obs_head"] = np.full(d_new, heads.min(), heads.dtype)
        extra["obs_bodies"] = row0(carry.obs_bodies)
        extra["obs_expanded"] = row0(carry.obs_expanded)
    return ShardCarry(
        table=table2,
        queue=queue2,
        qhead=np.zeros(d_new, np.int32),
        qtail=qtail2,
        level_end=lend2,
        level=repl(carry.level),
        depth=repl(carry.depth),
        generated=row0(carry.generated),
        distinct=distinct2,
        act_gen=row0(carry.act_gen),
        act_dist=row0(carry.act_dist),
        outdeg_hist=row0(carry.outdeg_hist),
        viol=repl(carry.viol),
        viol_state=vs2,
        viol_local=vl2,
        cont=repl(carry.cont),
        **extra,
    )


# ---------------------------------------------------------------------------
# the driver
# ---------------------------------------------------------------------------


class PodResult(NamedTuple):
    result: object  # engine.bfs.CheckResult
    exit_code: int
    host: int
    hosts: int
    segments: int
    resumed: bool
    resharded: bool
    checkpoint: Optional[str]
    spilled: int = 0
    spill_flushes: int = 0


def run_pod(
    cfg: ModelConfig = None,
    backend=None,
    *,
    chunk: int = 512,
    queue_capacity: int = 1 << 14,
    fp_capacity: int = 1 << 18,
    fp_index: int = None,
    seed: int = None,
    route_factor: float = 2.0,
    sort_free: bool = None,
    deferred: bool = None,
    obs_slots: int = 0,
    coverage: bool = False,
    ckpt_path: str = None,
    ckpt_every: int = 64,
    resume: bool = False,
    reshard: bool = False,
    spill: str = "off",
    spill_capacity: int = 1 << 22,
    fp_highwater: float = None,
    max_segments: int = None,
    meta_config: dict = None,
    workload: str = "kubeapi",
    journal: bool = True,
    progress_every: int = 1,
    on_event=None,
    devices: int = None,
) -> PodResult:
    """Drive one pod member to completion (or preemption) and return
    this process's PodResult.  Must run AFTER init_pod; every process
    of the pod calls it with IDENTICAL parameters (the collectives and
    make_array_from_callback constructors are pod-synchronous).

    chunk/queue_capacity/fp_capacity are PER DEVICE, exactly the
    sharded-engine contract - a pod of H hosts multiplies total table
    capacity by H at constant per-host memory, which is the scaling
    claim bench.py --multihost-ab commits.

    obs_slots > 0 turns the device counter ring on (per-host PARTIAL
    `level` events with a `host` field, decoded from this process's
    ring rows at each fence); coverage=True attaches the workload's
    CoveragePlane (per-host `coverage` delta events).  Both are pure
    telemetry - obs-on results are bit-for-bit obs-off results
    (bench.py --pod-obs-ab)."""
    import jax

    from ..engine.bfs import resolve_deferred, resolve_sort_free
    from ..engine.checkpoint import _meta, read_checkpoint_meta
    from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED
    from ..engine.sharded import (
        carry_to_global, cov_totals_local, kubeapi_backend,
        make_sharded_engine, obs_rows_sharded_local,
        result_from_shard_carry, shard_host_rows, shard_replace_rows,
        ShardedSpillRuntime,
    )
    from ..obs.coverage import coverage_delta_event
    from ..obs.phases import segment_phases

    fp_index = DEFAULT_FP_INDEX if fp_index is None else fp_index
    seed = DEFAULT_SEED if seed is None else seed
    if devices is not None and jax.process_count() > 1:
        raise ValueError("`devices` truncation is a single-process "
                         "test knob; a pod meshes every device")
    mesh = pod_mesh(devices)
    host, hosts = jax.process_index(), jax.process_count()
    D = int(mesh.devices.size)
    if cfg is None and backend is None:
        cfg = ModelConfig()
    if backend is None:
        backend = kubeapi_backend(cfg, coverage=coverage)
    if cfg is None and meta_config is None:
        meta_config = {"backend": "custom"}
    sort_free = resolve_sort_free(sort_free, chunk)
    deferred = resolve_deferred(deferred, chunk)
    spill_on = spill == "on"
    if spill_on and reshard:
        raise ValueError(
            "spill + reshard is unsupported: per-host SpillStores are "
            "keyed to the width that cut them - resume at the original "
            "width (ROADMAP #1 residue)"
        )
    red = getattr(backend, "reduce", None)
    meta = _meta(
        cfg if cfg is not None else ModelConfig(),
        meta_config=meta_config,
        queue_capacity=queue_capacity,
        fp_capacity=fp_capacity,
        devices=D,
        pipeline=False,
        obs_slots=obs_slots,
        sort_free=sort_free,
        deferred=deferred,
        symmetry=bool(red is not None and red.plan is not None),
        por=bool(red is not None and red.por and red.safe_ids),
        spill=spill_on,
        num_hosts=hosts,
    )

    jr = None
    if journal and ckpt_path is not None:
        from ..obs.journal import RunJournal

        jr = RunJournal(host_journal_path(ckpt_path, host),
                        resume=resume)

    def emit(kind, **fields):
        if jr is not None:
            jr.event(kind, **fields)
        if on_event is not None:
            on_event(kind, dict(fields))

    # resume validation FIRST: a wrong-width or wrong-mode snapshot
    # must refuse before the engine pays its AOT compile, not after
    resume_meta = resume_full = None
    if resume:
        if ckpt_path is None:
            raise ValueError("resume requires a checkpoint base path")
        my_path = host_checkpoint_path(ckpt_path, host)
        if reshard:
            resume_full = load_pod_full(ckpt_path)
            _validate_pod_meta(resume_full[0], meta, reshard=True)
            if resume_full[0].get("spill"):
                raise ValueError(
                    "reshard of a spill-mode pod checkpoint is "
                    "unsupported - resume at the original width"
                )
        else:
            resume_meta = read_checkpoint_meta(my_path)
            _validate_pod_meta(resume_meta, meta, reshard=False)

    # engine: the fused AOT segment loop, or the spill runtime's
    # expand/probe/commit protocol when the per-host lifeboat is on
    store = None
    rt = None
    if spill_on:
        from ..engine.spill import SpillStore

        store = SpillStore(spill_capacity)
        rt = ShardedSpillRuntime(
            cfg, mesh, chunk, queue_capacity, fp_capacity,
            fp_index=fp_index, seed=seed, route_factor=route_factor,
            backend=backend, fp_highwater=fp_highwater,
            obs_slots=obs_slots, sort_free=sort_free,
            deferred=deferred, store=store,
            on_event=lambda kind, info: emit(kind, host=host, **info),
        )
        template = rt.init_fn()
        seg = rt.segment_fn(ckpt_every)
    else:
        init_fn, seg_fn = make_sharded_engine(
            cfg, mesh, chunk, queue_capacity, fp_capacity,
            fp_index=fp_index, seed=seed, route_factor=route_factor,
            segment=ckpt_every, backend=backend, sort_free=sort_free,
            deferred=deferred, obs_slots=obs_slots,
        )
        template = init_fn()
        if hosts > 1:
            template = carry_to_global(mesh, template)
        seg = seg_fn.lower(template).compile()

    resumed = resharded = False
    carry = template
    if resume:
        if reshard:
            m0, carry_old = resume_full
            np_new = reshard_carry(
                carry_old, backend, D, queue_capacity=queue_capacity,
                fp_capacity=fp_capacity, fp_index=fp_index, seed=seed,
            )
            carry = carry_to_global(mesh, np_new)
            resharded = True
            emit("pod", phase="reshard", host=host, hosts=hosts,
                 old_hosts=int(m0.get("num_hosts", 1)), new_hosts=hosts,
                 old_devices=int(m0["devices"]), new_devices=D)
        else:
            m, payload = _load_host_payload(my_path)
            ids = [int(i) for i in m["host_rows"]]
            cur = sorted(shard_host_rows(template.table))
            if ids != cur:
                raise ValueError(
                    f"checkpoint host_rows mismatch: host {host} owns "
                    f"rows {cur} but the shard file holds {ids} - "
                    "launch hosts in their original order or --reshard"
                )
            for f, arr in payload.items():
                leaf = getattr(carry, f, None)
                if leaf is None:
                    raise ValueError(
                        f"checkpoint leaf {f!r} has no home in this "
                        "engine's carry - meta validation should have "
                        "caught this (corrupt shard?)"
                    )
                carry = carry._replace(**{f: shard_replace_rows(
                    leaf, {i: arr[k] for k, i in enumerate(ids)}
                )})
            if spill_on:
                from ..engine.spill import SpillStore, spill_sibling

                sib = spill_sibling(my_path)
                if os.path.exists(sib):
                    rt.store = store = SpillStore.load(sib)
        resumed = True
        emit("run_resume", version=__version__, path=my_path)
    else:
        emit("run_start", version=__version__, workload=workload,
             engine="pod", device=jax.devices()[0].platform,
             params=dict(chunk=chunk, queue_capacity=queue_capacity,
                         fp_capacity=fp_capacity, devices=D,
                         hosts=hosts, route_factor=route_factor,
                         sort_free=sort_free, deferred=deferred,
                         spill=spill_on, obs_slots=obs_slots,
                         coverage=(getattr(backend, "coverage", None)
                                   is not None)))
    emit("pod", phase="join", host=host, hosts=hosts)

    gather = make_stats_gather(mesh, carry)
    vote = make_stop_vote(mesh)

    # per-host obs cursors: each fence decodes only THIS process's new
    # ring rows / coverage movement (no extra collective - the fold
    # back to pod-global totals happens in obs.views over the sibling
    # journals).  fp_load is the host partial over the GLOBAL capacity
    # so the fold can SUM loads.  On resume the cursors seed from the
    # restored carry: journal and checkpoint are written at the same
    # fence, so replaying from the snapshot appends exactly the rows
    # the interrupted journal does not already hold.
    cov_plane = getattr(backend, "coverage", None)
    fp_total = fp_capacity * D
    obs_since = 0
    cov_seen = None
    cov_visited = cov_level = cov_last_new_level = 0
    cov_saturated = False
    if resumed:
        if obs_slots:
            _, obs_since = obs_rows_sharded_local(carry, since=1 << 30)
        if cov_plane is not None:
            cov_seen = cov_totals_local(carry)
            if cov_seen is not None:
                cov_visited = int((cov_seen > 0).sum())
        cov_level = cov_last_new_level = int(
            np.asarray(_first_row(carry.level))
        )

    def save_all(c, label="segment"):
        ts = time.time()
        path = save_pod_checkpoint(ckpt_path, c, meta, host)
        if store is not None:
            from ..engine.spill import spill_sibling

            store.save(spill_sibling(path))
        emit("checkpoint", path=path, seconds=time.time() - ts,
             label=label, host=host)
        return path

    flag = _SigtermFlag()
    flag.install()
    t0 = time.time()
    segments = 0
    preempted = False
    last_ckpt = None
    try:
        while bool(np.asarray(_first_row(carry.cont))):
            if max_segments is not None and segments >= max_segments:
                break
            t_dispatch = time.time()
            carry = jax.block_until_ready(seg(carry))
            t_fence = time.time()
            segments += 1
            tx = time.time()
            stop_now = vote(flag.hit)
            exchange_us = (time.time() - tx) * 1e6
            # obs at EVERY fence (checkpoint cadence, NOT progress
            # cadence): resume replays from the same fence the journal
            # last recorded, so the cursors give exactly-once rows
            emit("segment", index=segments - 1, host=host,
                 t_dispatch=t_dispatch, t_fence=t_fence,
                 wall_s=round(t_fence - t_dispatch, 6))
            for row in segment_phases(segments - 1,
                                      t_fence - t_dispatch):
                emit("phase", host=host, **row)
            if obs_slots:
                rows, obs_since = obs_rows_sharded_local(
                    carry, labels=backend.labels, since=obs_since,
                    fp_capacity_total=fp_total)
                for row in rows:
                    emit("level", host=host, **row)
                if rows:
                    cov_level = max(cov_level, rows[-1]["level"])
            if cov_plane is not None:
                totals = cov_totals_local(carry)
                payload = coverage_delta_event(
                    cov_plane.sites, totals, cov_seen)
                if payload is not None:
                    emit("coverage", host=host, **payload)
                    cov_seen = totals
                    if payload["visited"] > cov_visited:
                        cov_visited = payload["visited"]
                        cov_last_new_level = cov_level
                if (not cov_saturated and cov_visited
                        and cov_level - cov_last_new_level
                        >= COVERAGE_SAT_LEVELS):
                    cov_saturated = True
                    emit("coverage", host=host, visited=cov_visited,
                         sites=len(cov_plane.sites), delta={},
                         saturated=True, level=cov_level)
            if progress_every and segments % progress_every == 0:
                st = gather(carry)
                emit("progress", depth=int(st.depth.max()),
                     generated=int(st.generated.sum()),
                     distinct=int(st.distinct.sum()),
                     queue=int((st.qtail - st.qhead).sum()))
                local = shard_host_rows(carry.distinct)
                emit("pod", phase="stats", host=host, hosts=hosts,
                     shard_occupancy=(
                         max(int(v) for v in local.values())
                         / float(fp_capacity)),
                     spill_bytes=(store.count * 8
                                  if store is not None else 0),
                     exchange_us=exchange_us)
            if ckpt_path is not None:
                last_ckpt = save_all(carry)
            if stop_now:
                preempted = True
                break
    finally:
        flag.uninstall()
    wall = time.time() - t0

    st = gather(carry)
    result = result_from_shard_carry(
        st, wall, iterations=segments, labels=backend.labels,
        viol_names=backend.viol_names,
        fp_capacity_total=fp_capacity * D,
        sites=(cov_plane.sites if cov_plane is not None else None),
    )
    done = not bool(np.asarray(_first_row(carry.cont)))
    if preempted:
        emit("interrupted", signum=int(signal.SIGTERM), path=last_ckpt,
             generated=result.generated, distinct=result.distinct,
             queue=result.queue_left, wall_s=wall)
        emit("pod", phase="leave", host=host, hosts=hosts,
             path=last_ckpt)
        verdict, exit_code = "interrupted", EXIT_PREEMPTED
    elif result.violation:
        verdict, exit_code = "violation", EXIT_VIOLATION
    elif done:
        verdict, exit_code = "ok", EXIT_OK
    else:  # max_segments pause: journal closes valid, resume continues
        verdict, exit_code = "interrupted", EXIT_OK
    emit("final", verdict=verdict, generated=result.generated,
         distinct=result.distinct, depth=result.depth,
         queue=result.queue_left, wall_s=wall,
         interrupted=not (done or result.violation != 0))
    if jr is not None:
        jr.close()
    return PodResult(
        result=result, exit_code=exit_code, host=host, hosts=hosts,
        segments=segments, resumed=resumed, resharded=resharded,
        checkpoint=last_ckpt,
        spilled=(store.count if store is not None else 0),
        spill_flushes=(rt.flushes if rt is not None else 0),
    )
