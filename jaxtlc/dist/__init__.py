"""jaxtlc.dist: jax.distributed multi-host pods for the sharded engine.

One process per host, one global mesh over every host's devices, the
same compiled `make_sharded_engine` body throughout - the candidate-
routing `all_to_all` simply crosses DCN between hosts at the level-fence
seam the deferred collective already batches (engine/sharded.py module
docstring, "Topology").  This package adds only what distribution
genuinely needs on top:

* `pod.init_pod` / `pod.pod_mesh` - jax.distributed bring-up (gloo
  collectives on CPU pods) and the global "fp" mesh;
* `pod.run_pod` - the pod driver: AOT segment loop, per-host journals
  (`{base}.h{pid}.journal.jsonl`, merged by obs.serve's /runs registry
  and obs.views.merge_journals), per-host shard checkpoints
  (`{base}.h{pid}`), SIGTERM consensus (one preempted host checkpoints
  EVERY host via a pod-wide pmax vote, exit 75), and the per-host
  SpillStore lifeboat (`spill="on"`, ShardedSpillRuntime);
* `pod.reshard_carry` - resume at a DIFFERENT pod width: re-partitions
  saved table fingerprints and frontier states by the new owner mapping
  hi & (D'-1), host-side and exact.

`python -m jaxtlc.dist --spawn N` launches an N-process localhost pod
(the test/bench topology); see __main__.py.
"""

from .pod import (  # noqa: F401
    DEFAULT_COORDINATOR,
    EXIT_OK,
    EXIT_PREEMPTED,
    EXIT_VIOLATION,
    PodResult,
    host_checkpoint_path,
    host_journal_path,
    init_pod,
    pod_mesh,
    reshard_carry,
    run_pod,
)
