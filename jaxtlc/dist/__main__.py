"""Pod entry point: ``python -m jaxtlc.dist``.

Two modes:

* **worker** (default): join a pod as one process and run the KubeAPI
  workload to completion.  The three jax.distributed knobs are
  ``--coordinator --num-hosts --host``; everything else mirrors the
  engine parameters (per-device, like the sharded engine).  Prints one
  ``POD_RESULT {json}`` line (bench.py --multihost-ab parses it) and
  exits with the run's verdict code (0 ok / 12 violation / 75
  preempted-and-checkpointed).

* **launcher** (``--spawn N``): fork N localhost worker subprocesses
  around a fresh coordinator port - the test/bench topology, each
  worker a real jax.distributed process with its own device set (gloo
  collectives over loopback).  SIGTERM to the launcher forwards to
  every worker, so pod preemption drills work through it.

The module sets XLA's host-platform device count from
``--devices-per-host`` BEFORE any jax backend initializes (jaxtlc.dist
defers every jax import for exactly this reason); pass
``--devices-per-host 0`` to leave an externally-set XLA_FLAGS alone.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys

_TRI = {"auto": None, "on": True, "off": False}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m jaxtlc.dist",
        description="jax.distributed pod worker / localhost launcher",
    )
    p.add_argument("--spawn", type=int, default=0, metavar="N",
                   help="launcher mode: fork N localhost pod workers")
    p.add_argument("--coordinator", default=None,
                   help="host:port of process 0 (worker mode)")
    p.add_argument("--num-hosts", type=int, default=1)
    p.add_argument("--host", type=int, default=0,
                   help="this worker's jax process id")
    p.add_argument("--devices-per-host", type=int, default=1,
                   help="XLA host-platform device count per process "
                        "(0 = leave XLA_FLAGS alone)")
    p.add_argument("--ff", action="store_true",
                   help="requests_can_fail=requests_can_timeout=FALSE "
                        "(the small KubeAPI config; default is Model_1)")
    p.add_argument("--chunk", type=int, default=512)
    p.add_argument("--queue-capacity", type=int, default=1 << 14)
    p.add_argument("--fp-capacity", type=int, default=1 << 18)
    p.add_argument("--route-factor", type=float, default=2.0)
    p.add_argument("--sort-free", choices=tuple(_TRI), default="auto")
    p.add_argument("--deferred", choices=tuple(_TRI), default="auto")
    p.add_argument("--obs-slots", type=int, default=0,
                   help="device counter-ring slots (per-host `level` "
                        "events with a host field; 0 = off)")
    p.add_argument("--coverage", action="store_true",
                   help="attach the workload's CoveragePlane (per-host "
                        "`coverage` delta events)")
    p.add_argument("--ckpt", default=None,
                   help="checkpoint/journal base path (per-host files "
                        "{base}.h{pid} / {base}.h{pid}.journal.jsonl)")
    p.add_argument("--ckpt-every", type=int, default=64,
                   help="chunk steps per segment fence")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--reshard", action="store_true",
                   help="resume a checkpoint cut at a DIFFERENT pod "
                        "width (re-partitions the fingerprint space)")
    p.add_argument("--spill", choices=("off", "on"), default="off",
                   help="per-host SpillStore lifeboat for over-capacity "
                        "fingerprint tables")
    p.add_argument("--spill-capacity", type=int, default=1 << 22)
    p.add_argument("--max-segments", type=int, default=None)
    p.add_argument("--progress-every", type=int, default=1)
    return p


def _worker(args) -> int:
    if args.devices_per_host:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{args.devices_per_host}"
            ).strip()
    from . import DEFAULT_COORDINATOR, init_pod, run_pod
    from ..config import ModelConfig

    init_pod(args.coordinator or DEFAULT_COORDINATOR,
             args.num_hosts, args.host)
    cfg = ModelConfig(False, False) if args.ff else ModelConfig()
    pr = run_pod(
        cfg,
        chunk=args.chunk,
        queue_capacity=args.queue_capacity,
        fp_capacity=args.fp_capacity,
        route_factor=args.route_factor,
        sort_free=_TRI[args.sort_free],
        deferred=_TRI[args.deferred],
        obs_slots=args.obs_slots,
        coverage=args.coverage,
        ckpt_path=args.ckpt,
        ckpt_every=args.ckpt_every,
        resume=args.resume,
        reshard=args.reshard,
        spill=args.spill,
        spill_capacity=args.spill_capacity,
        max_segments=args.max_segments,
        progress_every=args.progress_every,
    )
    r = pr.result
    print("POD_RESULT " + json.dumps(dict(
        host=pr.host, hosts=pr.hosts, rc=pr.exit_code,
        generated=r.generated, distinct=r.distinct, depth=r.depth,
        queue=r.queue_left, violation=r.violation,
        outdegree=[round(float(v), 6) for v in r.outdegree],
        fp_occupancy=round(float(r.fp_occupancy), 6),
        action_generated={k: int(v)
                          for k, v in r.action_generated.items()},
        action_distinct={k: int(v)
                         for k, v in r.action_distinct.items()},
        wall_s=round(r.wall_s, 3), segments=pr.segments,
        resumed=pr.resumed, resharded=pr.resharded,
        spilled=pr.spilled, spill_flushes=pr.spill_flushes,
        checkpoint=pr.checkpoint,
    )), flush=True)
    return pr.exit_code


def _spawn(args, argv) -> int:
    coord = args.coordinator or f"127.0.0.1:{_free_port()}"
    child_argv = []
    skip = False
    for a in argv:  # strip "--spawn N" / "--spawn=N" from the worker argv
        if skip:
            skip = False
        elif a == "--spawn":
            skip = True
        elif not a.startswith("--spawn="):
            child_argv.append(a)
    procs = []
    for i in range(args.spawn):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "jaxtlc.dist", *child_argv,
             "--coordinator", coord, "--num-hosts", str(args.spawn),
             "--host", str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        ))

    def forward(signum, frame):  # pod preemption drills via the launcher
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)

    prev = signal.signal(signal.SIGTERM, forward)
    try:
        outs = [p.communicate()[0] for p in procs]
    finally:
        signal.signal(signal.SIGTERM, prev)
    rcs = [p.returncode for p in procs]
    sys.stdout.write(outs[0])
    for i, (rc, out) in enumerate(zip(rcs, outs)):
        if i and (rc not in (0, 75) or "POD_RESULT" not in out):
            tail = "\n".join(out.splitlines()[-12:])
            print(f"--- worker {i} rc={rc} tail ---\n{tail}",
                  file=sys.stderr)
    if 12 in rcs:
        return 12
    if 75 in rcs:
        return 75
    return max(rcs) if rcs else 1


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    args = _parser().parse_args(argv)
    if args.spawn:
        return _spawn(args, argv)
    return _worker(args)


if __name__ == "__main__":
    sys.exit(main())
