"""Device liveness orchestration: capture -> fixpoint -> validated lasso.

Two frontend entry points, both producing the SAME result types their
host-path counterparts produce, so the CLI rendering is path-agnostic:

* check_properties_device(cfg, props)  - the KubeAPI family
  (engine.liveness.LivenessResult with encoded field-vector states);
* check_leads_to_device(genspec, p, q) - generic-frontend specs
  (gen.oracle.LivenessResult with decoded state tuples).

Semantics are the host path's WF_vars(Next) reduction exactly
(engine.liveness module docstring); `wf_process` stays host-only - the
CLI routes it there.  Every violation is oracle-replayed before being
returned (live.lasso.replay_lasso); the differential tests additionally
pin whole-verdict and state-set equality against the host engines.

The device path is picked automatically above HOST_PATH_MAX distinct
states (where the host path's per-state Python dict becomes the
bottleneck); `-liveness-host` forces the old path.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..engine.liveness import LivenessResult as KubeLivenessResult
from .capture import CapturedGraph, capture_edges, eval_state_masks
from .fixpoint import has_nonself, surviving_set
from .lasso import build_lasso, replay_lasso

# above this many distinct states the host liveness graph (one Python
# dict entry + adjacency list per state) stops being viable; the device
# path has no per-state host objects at all
HOST_PATH_MAX = 1_000_000


def use_device_path(distinct: int, fairness: str = "wf_next",
                    force_host: bool = False) -> bool:
    """CLI dispatch rule: device path automatically above the host-path
    size threshold; wf_process and -liveness-host stay on the host path."""
    return (not force_host) and fairness == "wf_next" \
        and distinct > HOST_PATH_MAX


def _violation(graph, alive, in_h, trigger, name, labels,
               decode, is_initial, is_transition, equal=None):
    prefix_ids, cycle_ids, pre_act, cyc_act = build_lasso(
        graph, alive, in_h, trigger
    )
    prefix = [decode(i) for i in prefix_ids]
    cycle = [decode(i) for i in cycle_ids]
    replay_lasso(prefix, cycle, is_initial, is_transition, equal=equal)
    names = [None if a is None else labels[a] for a in pre_act]
    cnames = [None if a is None else labels[a] for a in cyc_act]
    return prefix_ids, cycle_ids, prefix, cycle, names, cnames


# ---------------------------------------------------------------------------
# KubeAPI family
# ---------------------------------------------------------------------------


def capture_kube_graph(cfg, chunk: int = 1024,
                       state_capacity: int = 1 << 20,
                       fp_capacity: int = 1 << 20,
                       spill_path: Optional[str] = None) -> CapturedGraph:
    from ..engine.sharded import kubeapi_backend

    return capture_edges(
        kubeapi_backend(cfg), chunk=chunk, state_capacity=state_capacity,
        fp_capacity=fp_capacity, spill_path=spill_path,
    )


def check_properties_device(
    cfg,
    properties: List[str],
    chunk: int = 1024,
    state_capacity: int = 1 << 20,
    fp_capacity: int = 1 << 20,
    mesh=None,
    graph: Optional[CapturedGraph] = None,
    spill_path: Optional[str] = None,
) -> List[KubeLivenessResult]:
    """Device-path analog of engine.liveness.check_properties (wf_next)."""
    import jax.numpy as jnp

    from ..spec import oracle
    from ..spec.codec import get_codec
    from ..spec.labels import LABELS

    cdc = get_codec(cfg)
    if graph is None:
        graph = capture_kube_graph(
            cfg, chunk=chunk, state_capacity=state_capacity,
            fp_capacity=fp_capacity, spill_path=spill_path,
        )
    nonself = has_nonself(graph)
    sr_off = cdc.offsets["sr"]
    api_sl = cdc.sl("api")

    def sr_fn(ri):
        return lambda f: f[:, sr_off + ri] == 1

    def secret_fn(ci):
        si, _ = cfg.targets[ci]

        def fn(f):
            api = f[:, api_sl]
            pres = (api >> cdc.o_present) & 1
            ident = (api >> cdc.o_ident) & ((1 << cdc.ib) - 1)
            return ((pres == 1) & (ident == si)).any(axis=1)

        return fn

    def decode_fields(i):
        row = jnp.asarray(graph.states[i][None])
        return np.asarray(cdc.unpack(row))[0].astype(np.int32)

    inits = set(oracle.initial_states(cfg))

    def is_initial(enc):
        return cdc.decode(np.asarray(enc)) in inits

    def is_transition(ea, eb):
        sa = cdc.decode(np.asarray(ea))
        sb = cdc.decode(np.asarray(eb))
        return sb in {x.state for x in oracle.successors(sa, cfg)}

    out: List[KubeLivenessResult] = []
    for name in properties:
        if cfg.n_reconcilers == 0:
            out.append(KubeLivenessResult(name, True, None, None))
            continue
        if name == "ReconcileCompletes":
            zones = [(sr_fn(ri), None) for ri in range(cfg.n_reconcilers)]
        elif name == "CleansUpProperly":
            zones = [
                (sr_fn(k), secret_fn(ci))
                for k, ci in enumerate(cfg.reconciler_indices)
            ]
        else:
            raise ValueError(f"unknown temporal property {name!r}")
        res = None
        for sr, secret in zones:
            if secret is None:
                # sr[c] ~> ~sr[c]: H = trigger = {sr[c]}
                (mask,) = eval_state_masks(graph, cdc, [sr])
                in_h = trigger = mask
            else:
                # []~sr[c] ~> absent: H = trigger = {~sr[c] /\ present}
                srm, pm = eval_state_masks(graph, cdc, [sr, secret])
                in_h = trigger = ~srm & pm
            alive, _ = surviving_set(graph, in_h, mesh=mesh,
                                     nonself=nonself)
            bad = trigger & alive
            if not bad.any():
                res = KubeLivenessResult(name, True, None, None)
                continue
            _, _, prefix, cycle, pnames, cnames = _violation(
                graph, alive, in_h, bad, name, LABELS,
                decode_fields, is_initial, is_transition,
                equal=np.array_equal,
            )
            res = KubeLivenessResult(name, False, prefix, cycle,
                                     pnames, cnames)
            break
        out.append(res)
    return out


# ---------------------------------------------------------------------------
# Generic frontend
# ---------------------------------------------------------------------------


def check_leads_to_device(
    spec,
    p_ast,
    q_ast,
    name: str = "",
    chunk: int = 1024,
    state_capacity: int = 1 << 20,
    fp_capacity: int = 1 << 20,
    mesh=None,
    graph: Optional[CapturedGraph] = None,
    spill_path: Optional[str] = None,
):
    """Device-path analog of gen.oracle.check_leads_to (wf_next)."""
    import jax

    from ..gen import oracle as go
    from ..gen.kernel import _Ctx, compile_expr
    from ..engine.sharded import gen_backend

    backend = gen_backend(spec)
    cdc = backend.cdc
    if graph is None:
        graph = capture_edges(
            backend, chunk=chunk, state_capacity=state_capacity,
            fp_capacity=fp_capacity, spill_path=spill_path,
        )
    ctx = _Ctx(codec=cdc, consts=dict(spec.constants), binding={}, at=None)
    masks = []
    for ast in (p_ast, q_ast):
        kind, fn = compile_expr(ast, ctx)
        if kind != "bool":
            raise ValueError(f"property operand is not BOOLEAN: {ast!r}")
        masks.append(jax.vmap(fn))
    p_mask, q_mask = eval_state_masks(graph, cdc, masks)
    in_h = ~q_mask
    alive, _ = surviving_set(graph, in_h, mesh=mesh)
    bad = p_mask & alive
    if not bad.any():
        return go.LivenessResult(name, True, None, None)

    init = go.initial_state(spec)

    def decode(i):
        import jax.numpy as jnp

        row = jnp.asarray(graph.states[i][None])
        return cdc.decode(np.asarray(cdc.unpack(row))[0])

    def is_transition(sa, sb):
        return any(
            nxt == sb and changed
            for _, nxt, changed in go.successors(spec, sa)
        )

    _, _, prefix, cycle, _, _ = _violation(
        graph, alive, in_h, bad, name, backend.labels,
        decode, lambda s: s == init, is_transition,
    )
    return go.LivenessResult(name, False, prefix, cycle)
