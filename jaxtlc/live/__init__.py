"""Device-resident liveness: on-device edge capture + tensorized
survive-set fixpoint (the product-graph subsystem SURVEY.md §7.10 named
as the missing piece before scaled configs get temporal checking).

Pipeline (live.check orchestrates):

1. **Enumerate** - the fused append-only state enumerator
   (engine.bfs.make_enumerator) materializes the reachable set on device
   in id order, one `lax.while_loop` dispatch.
2. **Capture** (live.capture) - a vectorized sweep re-expands every state
   through the same kernel, resolves each successor's id with a batched
   binary search over the sorted fingerprints, and emits the successor
   relation as (src, dst, action, state_changing) int32 tensors in
   fixed-capacity chunks, spilling through the checkpoint-style host tier
   when device capacity is exceeded.
3. **Fixpoint** (live.fixpoint) - the Kahn-style greatest-fixpoint
   peeling of engine.liveness, reformulated as converging masked
   scatter-reduce sweeps over the edge tensors inside a `lax.while_loop`,
   optionally sharded over the same mesh as the fingerprint set
   (engine.sharded.sharded_survive_fixpoint).
4. **Lasso** (live.lasso) - prefix + cycle reconstruction from the
   captured edges, validated by host-oracle replay.
"""

from .check import (  # noqa: F401
    HOST_PATH_MAX,
    check_leads_to_device,
    check_properties_device,
    use_device_path,
)
