"""Tensorized survive-set greatest fixpoint over captured edge tensors.

engine.liveness computes the surviving set by Kahn-style peeling over a
host CSR graph - O(E) total work but pointer-chasing and host-resident.
Here the same greatest fixpoint

    survive(s) iff s in H and (terminal(s)
                               or some state-changing successor in survive)

is computed as converging vectorized sweeps: one masked scatter-reduce
over the (src, dst) index tensors per sweep, inside a `lax.while_loop`,
entirely on device.  Sweep count is bounded by the peel depth of H's
subgraph (<= its longest simple path), each sweep is O(E) streaming work
- the BLEST/tensor-BFS trade (arXiv:2512.21967): more total FLOPs, no
per-state host round trips, so multi-million-state zones are feasible.

With a mesh, the edge tensors shard over the same axis as the
fingerprint set and the sweep reduces with a psum
(engine.sharded.sharded_survive_fixpoint).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .capture import CapturedGraph


def has_nonself(graph: CapturedGraph) -> np.ndarray:
    """[V] bool: state has at least one state-changing successor."""
    out = np.zeros(graph.n_states, bool)
    out[graph.src[graph.changed]] = True
    return out


def surviving_set(
    graph: CapturedGraph,
    in_h: np.ndarray,
    mesh=None,
    nonself: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, int]:
    """Greatest fixpoint over the restricted subgraph H.

    Terminal H-states (no state-changing successor anywhere in G) may
    stutter forever under WF_vars(Next); every other survivor needs a
    surviving state-changing successor inside H.  Returns
    (alive bool [V], sweeps)."""
    V = graph.n_states
    if nonself is None:
        nonself = has_nonself(graph)
    terminal = in_h & ~nonself
    # edges that can support survival: state-changing, internal to H
    keep = graph.changed & in_h[graph.src] & in_h[graph.dst]
    src = graph.src[keep]
    dst = graph.dst[keep]
    if mesh is not None and mesh.devices.size > 1:
        from ..engine.sharded import sharded_survive_fixpoint

        return sharded_survive_fixpoint(mesh, V, src, dst, in_h, terminal)

    src_j = jnp.asarray(src)
    dst_j = jnp.asarray(dst)

    @jax.jit
    def run(in_h_j, term_j):
        def body(st):
            alive, _, sweeps = st
            support = jnp.zeros(V, jnp.int32).at[src_j].max(
                alive[dst_j].astype(jnp.int32), mode="drop"
            )
            alive2 = alive & (term_j | (support > 0))
            return alive2, (alive2 != alive).any(), sweeps + 1

        return lax.while_loop(
            lambda st: st[1],
            body,
            (in_h_j, jnp.bool_(True), jnp.int32(0)),
        )

    alive, _, sweeps = jax.block_until_ready(
        run(jnp.asarray(in_h, bool), jnp.asarray(terminal, bool))
    )
    return np.asarray(alive), int(sweeps)
