"""Lasso reconstruction + host-oracle replay over captured edge tensors.

The violation certificate is TLC-style: a finite prefix from an initial
state to a surviving trigger state, then a cycle (or terminal stutter)
along surviving H-states.  Reconstruction is host-side - the lasso is a
few hundred states even on multi-million-state graphs - over numpy CSR
views of the captured (src, dst) tensors; no per-state Python objects
are ever built for the full graph.

Every reported lasso is REPLAYED through the frontend's host oracle
before it leaves this module: each consecutive pair must be a genuine
transition and the prefix must start at an initial state.  A lasso the
oracle cannot replay is a checker bug and raises, never prints.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import numpy as np

from .capture import CapturedGraph


class LassoError(RuntimeError):
    """A reconstructed counterexample failed oracle replay."""


class _CSR:
    """Forward adjacency over a (src, dst) edge subset."""

    def __init__(self, n: int, src: np.ndarray, dst: np.ndarray,
                 action: Optional[np.ndarray] = None):
        order = np.argsort(src, kind="stable")
        self.src = src[order]
        self.dst = dst[order]
        self.action = action[order] if action is not None else None
        self.starts = np.searchsorted(self.src, np.arange(n))
        self.ends = np.searchsorted(self.src, np.arange(n) + 1)

    def out(self, v: int) -> np.ndarray:
        return self.dst[self.starts[v]:self.ends[v]]

    def edge_action(self, u: int, v: int) -> Optional[int]:
        for e in range(self.starts[u], self.ends[u]):
            if self.dst[e] == v and self.action is not None:
                return int(self.action[e])
        return None


def _bfs_path(csr: _CSR, sources, target_mask) -> List[int]:
    """Shortest path from any source to any target (ids, inclusive)."""
    prev = {int(s): -1 for s in sources}
    queue = list(prev.keys())
    for s in queue:
        if target_mask[s]:
            return [s]
    qi = 0
    while qi < len(queue):
        v = queue[qi]
        qi += 1
        for w in csr.out(v):
            w = int(w)
            if w in prev:
                continue
            prev[w] = v
            if target_mask[w]:
                path = [w]
                while prev[path[-1]] != -1:
                    path.append(prev[path[-1]])
                path.reverse()
                return path
            queue.append(w)
    raise LassoError("no path found (graph invariant broken)")


def build_lasso(
    graph: CapturedGraph,
    survive: np.ndarray,
    in_h: np.ndarray,
    trigger: np.ndarray,
) -> Tuple[List[int], List[int], List[Optional[int]], List[Optional[int]]]:
    """(prefix_ids, cycle_ids, prefix_action_ids, cycle_action_ids).

    Prefix runs from an initial state to the first surviving trigger
    state; the cycle stays within survive (a single id when the state is
    a terminal stutter).  Action ids label the edge INTO each position
    (None for initial states / stutter)."""
    changed = graph.changed
    full = _CSR(graph.n_states, graph.src[changed], graph.dst[changed],
                graph.action[changed])
    bad = trigger & survive
    # prefix: initial state -> nearest surviving trigger state
    prefix_ids = _bfs_path(full, range(graph.init_count), bad)
    start = prefix_ids[-1]

    keep = changed & survive[graph.src] & survive[graph.dst] \
        & in_h[graph.src] & in_h[graph.dst]
    alive_csr = _CSR(graph.n_states, graph.src[keep], graph.dst[keep],
                     graph.action[keep])
    seen_at = {start: 0}
    walk = [start]
    cur = start
    while True:
        outs = alive_csr.out(cur)
        if not len(outs):
            # terminal stutter: the "cycle" is stuttering in place
            entry = len(walk) - 1
            cyc = walk[entry:]
            break
        nxt = int(outs[0])
        if nxt in seen_at:
            entry = seen_at[nxt]
            cyc = walk[entry:]
            break
        seen_at[nxt] = len(walk)
        walk.append(nxt)
        cur = nxt
    prefix = prefix_ids + walk[1:entry]

    def acts(ids: List[int], pred0: Optional[int]) -> List[Optional[int]]:
        preds = [pred0] + ids[:-1]
        return [
            None if p is None or p == i else full.edge_action(p, i)
            for p, i in zip(preds, ids)
        ]

    return (
        prefix,
        cyc,
        acts(prefix, None),
        acts(cyc, prefix[-1] if prefix else cyc[-1]),
    )


def replay_lasso(
    prefix_states: List,
    cycle_states: List,
    is_initial: Callable[[object], bool],
    is_transition: Callable[[object, object], bool],
    equal: Optional[Callable[[object, object], bool]] = None,
) -> None:
    """Oracle replay validation: raise LassoError unless every
    consecutive (decoded) pair is a genuine transition, the cycle closes,
    and the prefix starts at an initial state.  Stuttering pairs
    (equal states) are admissible steps under [][Next]_vars."""
    if equal is None:
        equal = lambda a, b: a == b  # noqa: E731
    chain = list(prefix_states) + list(cycle_states) + [cycle_states[0]]
    if not is_initial(chain[0]):
        raise LassoError("lasso prefix does not start at an initial state")
    for sa, sb in zip(chain, chain[1:]):
        if equal(sa, sb):
            continue
        if not is_transition(sa, sb):
            raise LassoError("lasso edge is not a real transition")
