"""On-device edge capture: the successor relation as index tensors.

Pass 1 (engine.bfs.make_enumerator) leaves the reachable set on device as
an append-only packed-state array whose row index is the state id.  This
module runs pass 2: every state is re-expanded through the same vmapped
kernel, each successor's id is resolved by a batched binary search over
the fingerprint-sorted state array (the tensor-core-BFS trick: the edge
relation never exists as host objects, only as index tensors), and the
deduplicated relation is emitted as (src, dst, action, state_changing)
int32 chunks.

Memory tiering: each sweep dispatch fills a fixed-capacity device chunk
(chunk * n_lanes edges); the host side accumulates drained chunks and -
when `spill_path` is set and the RAM budget is exceeded - spills them as
sequential .npz part files with the checkpoint tier's atomic
tmp-file + rename discipline (engine.checkpoint.save_checkpoint), so
multi-hundred-million-edge captures are disk-bounded like TLC's
DiskFPSet, not RAM-bounded.

Exactness: id resolution is fingerprint-based, so two distinct states
colliding on one 64-bit fingerprint would merge - exactly the risk class
the exhaustive engine already accepts and reports (MC.out:39-42); a
successor whose fingerprint is NOT in the enumerated set halts loudly
(it would mean the two passes disagree - a checker bug, never silent).
"""

from __future__ import annotations

import os
import zlib
from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..engine.bfs import OK, VIOLATION_NAMES, make_enumerator
from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words_mxu


class CapturedGraph(NamedTuple):
    """The device-captured reachable graph; ids are enumerator rows."""

    n_states: int
    init_count: int  # ids 0..init_count-1 are the initial states
    states: np.ndarray  # [V, W] uint32 packed states, id = row
    src: np.ndarray  # [E] int32
    dst: np.ndarray  # [E] int32
    action: np.ndarray  # [E] int32 action label id (backend.labels index)
    changed: np.ndarray  # [E] bool: state-changing edge (src != dst)


class _EdgeSpill:
    """Fixed-capacity host tier for drained edge chunks.

    Holds [n, 4] int32 blocks in RAM up to `ram_edges`; past that (and
    only when a spill path is given) full blocks are written as
    sequential .npz part files using the checkpoint tier's atomic
    tmp + rename discipline, and re-read once at finalize."""

    def __init__(self, spill_path: Optional[str] = None,
                 ram_edges: int = 1 << 26):
        self.spill_path = spill_path
        self.ram_edges = ram_edges
        self.blocks: List[np.ndarray] = []
        self.in_ram = 0
        self.parts: List[str] = []

    def append(self, block: np.ndarray) -> None:
        if not len(block):
            return
        self.blocks.append(block)
        self.in_ram += len(block)
        if self.spill_path is not None and self.in_ram > self.ram_edges:
            self._spill()

    def _spill(self) -> None:
        from ..engine.checkpoint import fsync_replace

        part = f"{self.spill_path}.edges{len(self.parts):05d}.npz"
        tmp = part + ".tmp"
        edges = np.concatenate(self.blocks)
        crc = np.uint32(zlib.crc32(np.ascontiguousarray(edges).tobytes()))
        with open(tmp, "wb") as f:
            np.savez_compressed(f, edges=edges, crc=crc)
            # fsync BEFORE the rename: os.replace alone orders only the
            # metadata, so a crash could publish a part file whose bytes
            # never hit the platter - recovered captures would then read
            # a torn edge relation
            fsync_replace(tmp, part, f=f)
        self.parts.append(part)
        self.blocks = []
        self.in_ram = 0

    def finalize(self) -> np.ndarray:
        loaded = []
        for part in self.parts:
            with np.load(part) as z:
                edges = z["edges"]
                if "crc" in z.files and zlib.crc32(
                    np.ascontiguousarray(edges).tobytes()
                ) != int(z["crc"]):
                    raise IOError(
                        f"edge-spill part {part!r} failed CRC verification "
                        "- torn write or bit rot; re-run the capture"
                    )
                loaded.append(edges)
            os.remove(part)
        if self.blocks:
            loaded.append(np.concatenate(self.blocks))
        if not loaded:
            return np.zeros((0, 4), np.int32)
        return np.concatenate(loaded)


def _pair_searchsorted(s_hi, s_lo, q_hi, q_lo, n: int):
    """Vectorized lower-bound binary search over (hi, lo) sorted pairs.

    jax has no uint64, so the 64-bit fingerprint stays as two uint32
    planes and the comparator is lexicographic; the static log2(n)
    unrolled rounds are each one gather."""
    lo_i = jnp.zeros(q_hi.shape, jnp.int32)
    hi_i = jnp.full(q_hi.shape, n, jnp.int32)
    for _ in range(max(1, (n - 1).bit_length())):
        cont = lo_i < hi_i
        mid = (lo_i + hi_i) >> 1
        m_hi = s_hi[jnp.minimum(mid, n - 1)]
        m_lo = s_lo[jnp.minimum(mid, n - 1)]
        less = (m_hi < q_hi) | ((m_hi == q_hi) & (m_lo < q_lo))
        lo_i = jnp.where(cont & less, mid + 1, lo_i)
        hi_i = jnp.where(cont & ~less, mid, hi_i)
    return lo_i


def capture_edges(
    backend,
    chunk: int = 1024,
    state_capacity: int = 1 << 20,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    spill_path: Optional[str] = None,
    ram_edges: int = 1 << 26,
) -> CapturedGraph:
    """Enumerate the reachable set and capture its edge relation.

    `backend` is an engine.sharded.SpecBackend (kubeapi_backend or
    gen_backend), so any spec the sharded engine can run gets its graph
    captured with zero per-state host work.
    """
    cdc = backend.cdc
    F = cdc.n_fields
    W = (cdc.nbits + 31) // 32
    L = backend.n_lanes
    nbits = cdc.nbits
    ncand = chunk * L
    init_count = int(np.asarray(backend.initial_vectors()).shape[0])

    # ---- pass 1: fused enumeration (ids = append order) ----
    init_fn, run_fn = make_enumerator(
        backend, chunk=chunk, state_capacity=state_capacity,
        fp_capacity=fp_capacity, fp_index=fp_index, seed=seed,
    )
    carry = jax.block_until_ready(run_fn(init_fn()))
    code = int(carry.viol)
    if code != OK:
        raise RuntimeError(
            f"liveness enumeration halted: {VIOLATION_NAMES[code]}"
        )
    V = int(carry.tail)
    states_np = np.asarray(carry.states)[:V]
    del carry
    states = jnp.asarray(states_np)

    # ---- fingerprint-sorted id map ----
    lo, hi = fp64_words_mxu(states, nbits, fp_index, seed)
    s_hi, s_lo, perm = lax.sort(
        (hi, lo, jnp.arange(V, dtype=jnp.int32)), num_keys=2
    )

    # states padded to a whole number of sweep chunks
    Vp = -(-V // chunk) * chunk
    states_pad = jnp.zeros((Vp, W), jnp.uint32).at[:V].set(states)
    step = backend.step

    @jax.jit
    def sweep(offset):
        block = lax.dynamic_slice(
            states_pad, (offset, jnp.int32(0)), (chunk, W)
        )
        batch = cdc.unpack(block)
        succs, valid, action, _afail, _ovf = jax.vmap(step)(batch)
        rows = jnp.arange(chunk, dtype=jnp.int32)
        valid = valid & ((offset + rows) < V)[:, None]
        flat = succs.reshape(ncand, F)
        fvalid = valid.reshape(-1)
        faction = jnp.broadcast_to(action, (chunk, L)).reshape(-1)
        packed = cdc.pack(flat)
        q_lo, q_hi = fp64_words_mxu(packed, nbits, fp_index, seed)
        idx = _pair_searchsorted(s_hi, s_lo, q_hi, q_lo, V)
        idx_c = jnp.minimum(idx, V - 1)
        found = (s_hi[idx_c] == q_hi) & (s_lo[idx_c] == q_lo) & (idx < V)
        dst = perm[idx_c]
        srcf = offset + jnp.arange(ncand, dtype=jnp.int32) // L
        changed = dst != srcf
        missing = (fvalid & ~found).any()
        # compact the valid edges to the front: one fixed-capacity chunk
        # of (src, dst, action, changed) per dispatch
        _, order = lax.sort(
            ((~fvalid).astype(jnp.uint32),
             jnp.arange(ncand, dtype=jnp.uint32)),
            num_keys=1, is_stable=True,
        )
        edges = jnp.stack(
            [srcf, dst, faction.astype(jnp.int32),
             changed.astype(jnp.int32)], axis=1,
        )[order]
        return edges, fvalid.sum(), missing

    spillway = _EdgeSpill(spill_path, ram_edges=ram_edges)
    for off in range(0, Vp, chunk):
        edges, nv, missing = sweep(jnp.int32(off))
        if bool(missing):
            raise RuntimeError(
                "edge capture found a successor outside the enumerated "
                "set (enumeration/capture disagree - checker bug)"
            )
        spillway.append(np.asarray(edges[: int(nv)]))
    raw = spillway.finalize()

    # dedup parallel (src, dst, action) triples; `changed` is determined
    # by (src, dst), so it survives dedup unchanged
    if len(raw):
        n_act = int(raw[:, 2].max()) + 1
        key = (
            raw[:, 0].astype(np.int64) * V + raw[:, 1].astype(np.int64)
        ) * n_act + raw[:, 2].astype(np.int64)
        _, uniq = np.unique(key, return_index=True)
        raw = raw[np.sort(uniq)]
    return CapturedGraph(
        n_states=V,
        init_count=init_count,
        states=states_np,
        src=raw[:, 0].astype(np.int32),
        dst=raw[:, 1].astype(np.int32),
        action=raw[:, 2].astype(np.int32),
        changed=raw[:, 3].astype(bool),
    )


def eval_state_masks(graph: CapturedGraph, cdc, fns, chunk: int = 8192):
    """Evaluate per-state bool predicates over the captured states.

    fns: list of (fields [B, F] -> bool [B]) vectorized predicates; the
    states are unpacked chunk-wise on device so scaled captures never
    materialize the [V, F] field matrix on host.  Returns a list of
    np.bool_ [V] masks aligned with state ids."""
    V = graph.n_states
    W = graph.states.shape[1]
    Vp = -(-max(V, 1) // chunk) * chunk
    pad = jnp.zeros((Vp, W), jnp.uint32).at[:V].set(
        jnp.asarray(graph.states)
    )

    @jax.jit
    def one(offset):
        block = lax.dynamic_slice(pad, (offset, jnp.int32(0)), (chunk, W))
        fields = cdc.unpack(block)
        return [fn(fields) for fn in fns]

    outs = [[] for _ in fns]
    for off in range(0, Vp, chunk):
        res = one(jnp.int32(off))
        for k, r in enumerate(res):
            outs[k].append(np.asarray(r))
    return [np.concatenate(o)[:V] for o in outs]
