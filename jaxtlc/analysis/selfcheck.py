"""Audit every shipped engine factory (the preflight's own CI).

`python -m jaxtlc.analysis --self-check --tiny` builds each production
engine factory at tiny geometry, traces its run/step jaxprs and runs
the engine-layer audit suite (purity, donation tags, counter widths).
The registry below IS the definition of "shipped": a new engine path
added without a registry entry fails the tier-1 smoke test
(tests/test_analysis.py pins the factory list), so no engine can ship
unaudited.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from . import AnalysisReport, Finding
from .engine_audit import audit_engine, carry_shapes

# tiny self-check geometry: enough rows for the FF inits, nothing more
_TINY = dict(chunk=16, queue_capacity=1 << 8, fp_capacity=1 << 10)


def _ff_backend():
    from ..config import ModelConfig
    from ..engine.backend import kubeapi_backend

    return kubeapi_backend(ModelConfig(False, False))


def _build_fused():
    from ..engine.bfs import make_backend_engine

    init_fn, run_fn, step_fn = make_backend_engine(
        _ff_backend(), donate=False, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=_ff_backend().n_lanes,
                fp_capacity=_TINY["fp_capacity"])


def _build_pipelined():
    from ..engine.bfs import make_backend_engine

    init_fn, run_fn, step_fn = make_backend_engine(
        _ff_backend(), donate=False, pipeline=True, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=_ff_backend().n_lanes,
                fp_capacity=_TINY["fp_capacity"])


def _build_sharded():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..engine.sharded import make_sharded_engine

    mesh = Mesh(np.array(jax.devices()[:1]), ("fp",))
    init_fn, run_fn = make_sharded_engine(
        None, mesh, backend=_ff_backend(), **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn,
                n_lanes=_ff_backend().n_lanes,
                fp_capacity=_TINY["fp_capacity"])


def _specs_dir() -> Optional[str]:
    import os

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cand = os.path.join(os.path.dirname(here), "specs")
    return cand if os.path.isdir(cand) else None


def _build_struct():
    import os

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    b = get_backend(model, True)
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_narrowed():
    # the certified-bound narrowed struct engine (ISSUE 10): the same
    # TwoPhase model as "struct" but compiled against the certified
    # reachable bounds with the runtime certificate check on - the
    # narrowed codec + cert column path cannot ship unaudited
    import os

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend, get_bounds
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    bounds = get_bounds(model)
    b = get_backend(model, True, bounds=bounds)
    assert b.cert_check is not None, "narrowed factory must carry cert"
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, obs_slots=8, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_covered():
    # the device coverage plane engine (ISSUE 11): the same TwoPhase
    # model as "struct" but compiled with the per-site coverage
    # counters + the obs ring - the covered carry layout (cov_counts
    # leaf) cannot ship unaudited
    import os

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    b = get_backend(model, True, coverage=True)
    assert b.coverage is not None, "covered factory must carry a plane"
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, obs_slots=8, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_covsharded():
    # the pod obs MESH engine (ISSUE 20): the sharded owner-commit
    # engine with the counter ring + coverage plane riding its carry -
    # the per-shard cov_counts leaf and ring rows the pod driver
    # checkpoints, reads at fences and migrates on --reshard cannot
    # ship unaudited
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..config import ModelConfig
    from ..engine.backend import kubeapi_backend
    from ..engine.sharded import make_sharded_engine

    b = kubeapi_backend(ModelConfig(False, False), coverage=True)
    assert b.coverage is not None, "covsharded factory needs a plane"
    mesh = Mesh(np.array(jax.devices()[:1]), ("fp",))
    init_fn, run_fn = make_sharded_engine(
        None, mesh, backend=b, obs_slots=8, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_sortfree():
    # the hash-slab commit engine (ISSUE 12): the same TwoPhase model
    # as "struct" but committed through the sort-free dedup, with the
    # obs ring + coverage plane riding along - the slab scatter/gather
    # path and its sorted-fallback cond cannot ship unaudited
    import os

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    b = get_backend(model, True, coverage=True)
    assert b.coverage is not None, "sortfree factory must carry a plane"
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, obs_slots=8, sort_free=True, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_deferred():
    # the distinct-first deferred-evaluation engine (ISSUE 15): the
    # same TwoPhase model as "struct" but with invariant + certificate
    # evaluation moved to the commit stage (fresh-insert claimants
    # only), the obs ring riding along - the commit-site checker's
    # gather/while_loop path cannot ship unaudited
    import os

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    b = get_backend(model, True)
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, obs_slots=8, deferred=True, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_sim():
    # the random-walk simulation engine (jaxtlc.sim, ISSUE 14): the
    # same TwoPhase model as "struct", walked with the counter-based
    # RNG and the fp sampling filter - the chosen-successor gather,
    # threefry draw and saturating filter path cannot ship unaudited
    import os

    from ..sim.engine import make_sim_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    b = get_backend(model, True)
    init_fn, run_fn, step_fn = make_sim_engine(
        b, walkers=8, depth=8, fp_capacity=1 << 10,
    )
    return dict(init_fn=lambda: init_fn(0), run_fn=run_fn,
                step_fn=step_fn, n_lanes=b.n_lanes,
                fp_capacity=1 << 10)


def _build_infer():
    # the inference filter/certify kernels (jaxtlc.infer, ISSUE 16):
    # the same TwoPhase model as "struct", its conjectured candidate
    # pool compiled into the [P, S] filter dispatch (run_fn) and the
    # one-step closure certify dispatch (step_fn) - the vmapped
    # stacked-predicate path cannot ship unaudited
    import os

    from ..infer.candidates import conjecture
    from ..infer.certify import make_certify_fn
    from ..infer.filter import (
        compile_predicates,
        make_filter_fn,
        predicate_compiler,
    )
    from ..struct.cache import get_backend, get_bounds
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_1",
                              "MC.cfg"))
    b = get_backend(model, True)
    cands, _ = conjecture(model, bounds=get_bounds(model), budget=16)
    fns, _ = compile_predicates(predicate_compiler(model, b), cands)

    def init_fn():
        import jax.numpy as jnp

        return jnp.zeros((16, b.cdc.n_fields), jnp.int32)

    return dict(init_fn=init_fn, run_fn=make_filter_fn(fns),
                step_fn=make_certify_fn(b, fns), n_lanes=b.n_lanes,
                fp_capacity=_TINY["fp_capacity"])


def _build_symmetry():
    # the symmetry-reduced engine (engine.reduce, ISSUE 18): the
    # TwoPhase model with a 3-element symmetric RM set, compiled with
    # the on-device orbit canonicalization + the sticky COL_SYM orbit
    # certificate - the permutation-program tournament and the ring's
    # tenth column cannot ship unaudited
    import os

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = _specs_dir()
    if d is None:
        raise FileNotFoundError("specs/ directory not found")
    model = load(os.path.join(d, "TwoPhase.toolbox", "Model_sym",
                              "MC.cfg"))
    b = get_backend(model, False, symmetry=True)
    assert b.reduce is not None and b.reduce.plan is not None, \
        "symmetry factory must carry an orbit plan"
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, obs_slots=8, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


_POR_SPEC = """---- MODULE PorAudit ----
EXTENDS Naturals
VARIABLES x, y

Init == x = 0 /\\ y = 0

IncX == /\\ x < 4
        /\\ x' = x + 1
        /\\ UNCHANGED <<y>>

IncY == /\\ y < 4
        /\\ y' = y + 1
        /\\ UNCHANGED <<x>>

Next == IncX \\/ IncY

Spec == Init /\\ [][Next]_<<x, y>>

InRange == x <= 4
====
"""

_POR_CFG = """SPECIFICATION
Spec
INVARIANT
InRange
"""


def _build_por():
    # the partial-order-pruned engine (engine.reduce, ISSUE 18):
    # audited over a synthetic two-counter module whose IncY is a POR-
    # safe action (independent, invisible to the invariant, monotone;
    # frame conjuncts MUST be UNCHANGED or speclint counts them as
    # writes) - the singleton-ample lane-mask path cannot ship
    # unaudited
    import os
    import tempfile

    from ..engine.bfs import make_backend_engine
    from ..struct.cache import get_backend
    from ..struct.loader import load

    d = tempfile.mkdtemp(prefix="jaxtlc-por-audit-")
    with open(os.path.join(d, "PorAudit.tla"), "w") as f:
        f.write(_POR_SPEC)
    cfg = os.path.join(d, "PorAudit.cfg")
    with open(cfg, "w") as f:
        f.write(_POR_CFG)
    model = load(cfg)
    b = get_backend(model, False, por=True)
    assert b.reduce is not None and b.reduce.safe_ids, \
        "por factory must carry safe action ids"
    init_fn, run_fn, step_fn = make_backend_engine(
        b, donate=False, obs_slots=8, **_TINY
    )
    return dict(init_fn=init_fn, run_fn=run_fn, step_fn=step_fn,
                n_lanes=b.n_lanes, fp_capacity=_TINY["fp_capacity"])


def _build_enumerator():
    from ..engine.bfs import make_enumerator

    init_fn, run_fn = make_enumerator(
        _ff_backend(), chunk=16, state_capacity=1 << 10,
        fp_capacity=1 << 10,
    )
    return dict(init_fn=init_fn, run_fn=run_fn,
                n_lanes=_ff_backend().n_lanes, fp_capacity=1 << 10)


def _build_spill():
    # the spill-capable engine: the DEVICE composition (expand +
    # fpset_member filter + veto commit) is traced as one step; the
    # host probe sits between the two jits in production, outside any
    # device body, which is exactly what the purity audit verifies
    from ..engine.spill import SpillRuntime, SpillStore

    rt = SpillRuntime(
        _ff_backend(), chunk=_TINY["chunk"],
        queue_capacity=_TINY["queue_capacity"],
        fp_capacity=_TINY["fp_capacity"],
        store=SpillStore(1 << 10),
    )
    return dict(init_fn=rt.init_fn, step_fn=rt.audit_step_fn,
                n_lanes=_ff_backend().n_lanes,
                fp_capacity=_TINY["fp_capacity"])


def _build_shardspill():
    # the spill-capable MESH engine (ISSUE 19): the audited step is the
    # expand half (candidate-routing all_to_all + owner fpset_member
    # filter) composed with the veto commit half; the host SpillStore
    # probe sits between the two shard_map dispatches in production,
    # outside any device body - exactly what the purity audit verifies
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from ..engine.sharded import ShardedSpillRuntime
    from ..engine.spill import SpillStore

    mesh = Mesh(np.array(jax.devices()[:1]), ("fp",))
    rt = ShardedSpillRuntime(
        None, mesh, _TINY["chunk"], _TINY["queue_capacity"],
        _TINY["fp_capacity"], backend=_ff_backend(),
        store=SpillStore(1 << 10),
    )
    return dict(init_fn=rt.init_fn, step_fn=rt.audit_step_fn,
                n_lanes=_ff_backend().n_lanes,
                fp_capacity=_TINY["fp_capacity"])


_SWEEP_SPEC = """---- MODULE SweepAudit ----
EXTENDS Naturals
CONSTANTS MAX
VARIABLES x

Init == x = 0

Up == /\\ x < MAX
      /\\ x' = x + 1

Next == Up

Spec == Init /\\ [][Next]_x

InRange == x <= MAX
====
"""

_SWEEP_CFG = """CONSTANT MAX = 3
SPECIFICATION
Spec
INVARIANT
InRange
"""


def _build_sweep():
    # the constants-class sweep engine (jaxtlc.serve.sweep): audited
    # over a synthetic one-constant module so the registry never
    # depends on serve-side fixtures; init_fn presents the stacked
    # width-2 batch carry the vmapped run_fn consumes
    import os
    import tempfile

    from ..serve.sweep import SweepEngine, load_anchored

    d = tempfile.mkdtemp(prefix="jaxtlc-sweep-audit-")
    with open(os.path.join(d, "SweepAudit.tla"), "w") as f:
        f.write(_SWEEP_SPEC)
    cfg = os.path.join(d, "SweepAudit.cfg")
    with open(cfg, "w") as f:
        f.write(_SWEEP_CFG)
    params = {"MAX": (1, 3)}
    model = load_anchored(cfg, params)
    eng = SweepEngine(
        model, params, chunk=_TINY["chunk"],
        queue_capacity=_TINY["queue_capacity"],
        fp_capacity=_TINY["fp_capacity"], check_deadlock=False,
        width=2,
    )

    def init_fn():
        return eng._stack([{"MAX": 1}, {"MAX": 3}])

    return dict(init_fn=init_fn, run_fn=eng._vrun,
                n_lanes=eng.backend.n_lanes,
                fp_capacity=_TINY["fp_capacity"])


def _build_phased():
    # the -phase-timing engine wrapper (obs.phases.PhasedRuntime): the
    # DEVICE composition (separately-jitted expand + commit halves) is
    # traced as one step; the fences sit between the two jits on the
    # host, outside any device body - what the purity audit verifies
    from ..obs.phases import PhasedRuntime

    rt = PhasedRuntime(
        _ff_backend(), chunk=_TINY["chunk"],
        queue_capacity=_TINY["queue_capacity"],
        fp_capacity=_TINY["fp_capacity"],
    )
    return dict(init_fn=rt.init_fn, step_fn=rt.audit_step_fn,
                n_lanes=_ff_backend().n_lanes,
                fp_capacity=_TINY["fp_capacity"])


# every shipped engine factory; audited by the self-check and pinned
# by tier-1 so a new engine path cannot ship unaudited
FACTORIES: Dict[str, Callable[[], dict]] = {
    "covered": _build_covered,
    "covsharded": _build_covsharded,
    "deferred": _build_deferred,
    "fused": _build_fused,
    "infer": _build_infer,
    "narrowed": _build_narrowed,
    "phased": _build_phased,
    "pipelined": _build_pipelined,
    "por": _build_por,
    "sharded": _build_sharded,
    "shardspill": _build_shardspill,
    "sim": _build_sim,
    "sortfree": _build_sortfree,
    "spill": _build_spill,
    "struct": _build_struct,
    "sweep": _build_sweep,
    "symmetry": _build_symmetry,
    "enumerator": _build_enumerator,
}


def self_check(tiny: bool = True, out=None) -> AnalysisReport:
    """Build + audit every registered factory.  `tiny` is accepted for
    CLI symmetry; the registry always builds tiny geometries (the audit
    is geometry-independent - jaxprs, not runs)."""
    import sys
    import time

    out = out or sys.stdout
    t0 = time.time()
    report = AnalysisReport(name="self-check")
    for name in sorted(FACTORIES):
        try:
            built = FACTORIES[name]()
        except FileNotFoundError as e:
            out.write(f"audit {name}: SKIPPED ({e})\n")
            continue
        carry = carry_shapes(built["init_fn"])
        findings: List[Finding] = audit_engine(
            name,
            built["init_fn"],
            built.get("run_fn"),
            built.get("step_fn"),
            reuses_carry=built.get("reuses_carry", False),
            fp_capacity=built.get("fp_capacity"),
            n_lanes=built.get("n_lanes"),
            trace=True,
            carry=carry,
        )
        report.extend(findings)
        status = "ok" if not findings else (
            f"{len(findings)} finding(s)"
        )
        out.write(f"audit {name}: {status}\n")
        report.engine_lines.append(f"{name}: {status}")
    report.wall_s = time.time() - t0
    out.write(
        f"self-check: {len(FACTORIES)} factories, "
        f"{len(report.findings)} finding(s), "
        f"{report.wall_s:.2f}s\n"
    )
    return report
