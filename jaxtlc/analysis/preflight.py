"""Preflight orchestration: the suite the CLI runs before a check.

Lite mode (default, `-no-preflight` disables) costs milliseconds: the
spec-layer lints (struct specs - pure host Python over the IR) and the
static counter-width arithmetic.  Deep mode (`-analyze`) adds the
jaxpr purity trace of the engine the run is about to use - tracing
only, never an extra XLA compile (struct backends come from the same
memo the run uses, so even the Python lane-compile is shared).
"""

from __future__ import annotations

import time
from typing import Optional

from . import AnalysisReport
from .engine_audit import audit_counter_width, audit_engine


def preflight_struct(model, *, fp_capacity: int, chunk: int,
                     queue_capacity: int, check_deadlock: bool = True,
                     deep: bool = False,
                     backend=None, bounds=None, narrow: bool = False,
                     symmetry: bool = False,
                     const_hints=None,
                     extra_init_systems=()) -> AnalysisReport:
    """Struct-path preflight: spec lints + engine-layer arithmetic;
    deep mode traces the (memoized) struct engine's step.  `bounds`
    (absint.BoundReport - or True to compute one here) adds the
    certified-bound report section and its findings; `narrow` marks
    that the run intends to use the narrowed codec, which escalates an
    uncertified report to a visible warning; `symmetry` marks that the
    run already reduces by symmetry, which silences the unreduced-
    symmetry nudge.  `const_hints` / `extra_init_systems` widen the
    analysis over a sweep constants CLASS (jaxtlc.analysis --sweep)."""
    from .speclint import analyze_spec

    t0 = time.time()
    report = AnalysisReport(name=f"struct:{model.root_name}")
    dynamic = frozenset(const_hints or ())
    spec = analyze_spec(model, dynamic_consts=dynamic,
                        const_hints=const_hints)
    report.spec = spec
    report.extend(spec.findings)
    if not symmetry:
        # the spec qualifies for orbit dedup but the run is not taking
        # it: one warning per SYMMETRY-eligible constant set (ISSUE 18)
        from .symfind import unreduced_symmetry_findings

        report.extend(unreduced_symmetry_findings(model))
    if bounds is True or (bounds is None and (const_hints
                                              or extra_init_systems)):
        from .absint import analyze_bounds

        bounds = analyze_bounds(model, const_hints=const_hints,
                                extra_init_systems=extra_init_systems)
    if bounds is not None:
        report.bound_lines = bounds.render_lines()
        report.extend(bounds.findings())
        if narrow and not bounds.certified:
            # the -narrow request could not be honored; the run
            # proceeds on the baseline layout - say so loudly enough
            # that the user notices the flag did nothing
            from . import SEV_WARNING, Finding

            report.findings.append(Finding(
                layer="spec", check="narrow-refused",
                severity=SEV_WARNING, subject=model.root_name,
                detail=("-narrow requested but the bound report is "
                        "not certified; running with the baseline "
                        "(un-narrowed) codec"),
            ))
    n_lanes = None
    if backend is None and deep:
        from ..struct.cache import get_backend

        backend = get_backend(model, check_deadlock)
    if backend is not None:
        n_lanes = backend.n_lanes
    else:
        # lite bound without building the backend: every action branch
        # is at least one lane, action-position binders multiply - use
        # the branch count as the static lower bound
        n_lanes = sum(a.n_branches for a in spec.actions.values()) or 1
    report.extend(audit_counter_width(
        f"struct:{model.root_name}", fp_capacity, n_lanes
    ))
    if deep and backend is not None:
        from ..engine.bfs import make_backend_engine

        init_fn, run_fn, step_fn = make_backend_engine(
            backend, chunk=chunk, queue_capacity=queue_capacity,
            fp_capacity=fp_capacity, donate=False,
        )
        report.extend(audit_engine(
            "struct-engine", init_fn, run_fn, step_fn,
            reuses_carry=False, trace=True,
        ))
        from .engine_audit import carry_shapes, describe_engine

        report.engine_lines.append(describe_engine(
            "struct-engine.run_fn", run_fn, carry_shapes(init_fn),
            extras=(f"lanes={backend.n_lanes}",
                    f"labels={len(backend.labels)}"),
        ))
    report.wall_s = time.time() - t0
    return report


def preflight_kubeapi(cfg, *, fp_capacity: int, chunk: int,
                      queue_capacity: int,
                      deep: bool = False) -> AnalysisReport:
    """Hand-kernel (KubeAPI) preflight: the spec layer does not apply
    (no struct IR); the engine layer audits counter widths from the
    static lane layout, plus the traced engine in deep mode."""
    from ..spec.kernel import lane_layout

    t0 = time.time()
    _, n_lanes = lane_layout(cfg)
    report = AnalysisReport(name="kubeapi:Model")
    report.extend(audit_counter_width("kubeapi", fp_capacity, n_lanes))
    if deep:
        from ..engine.bfs import make_engine

        init_fn, run_fn, step_fn = make_engine(
            cfg, chunk=chunk, queue_capacity=queue_capacity,
            fp_capacity=fp_capacity, donate=False,
        )
        report.extend(audit_engine(
            "kubeapi-engine", init_fn, run_fn, step_fn,
            reuses_carry=False, trace=True,
        ))
        from .engine_audit import carry_shapes, describe_engine

        report.engine_lines.append(describe_engine(
            "kubeapi-engine.run_fn", run_fn, carry_shapes(init_fn),
            extras=(f"lanes={n_lanes}",),
        ))
    report.wall_s = time.time() - t0
    return report


def preflight_gen(genspec, *, fp_capacity: int,
                  deep: bool = False) -> AnalysisReport:
    """Generic-frontend preflight: counter-width arithmetic only (the
    gen IR predates the struct IR the spec lints read; its subset specs
    are small enough that the runtime traps cover the rest)."""
    t0 = time.time()
    report = AnalysisReport(name=f"gen:{getattr(genspec, 'name', '?')}")
    n_lanes = max(len(getattr(genspec, "actions", ())), 1)
    report.extend(audit_counter_width("gen", fp_capacity, n_lanes))
    report.wall_s = time.time() - t0
    return report
