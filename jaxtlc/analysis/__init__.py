"""Preflight static-analysis plane (spec IR lints + engine jaxpr audits).

TLC front-loads whole classes of failures before the expensive search
starts (config/spec sanity checks, the level-0 evaluation pass -
PAPER.md §L4, §2.3); jaxtlc historically discovered its equivalents at
runtime, on device, mid-run.  This package is the preflight analog:

* **Spec layer** (`speclint`, over the struct frontend's IR - parsed
  ASTs + inferred shapes + codec layout): per-action read/write
  variable sets and the action independence graph, unreachable-action
  and invariant-vacuity lints, and a static codec-slot/trap budget
  audit (the RaftReplication "codec slot overflow" class becomes a
  named compile-time diagnostic instead of a device mystery).
* **Engine layer** (`engine_audit`, over jaxprs traced from the
  engine factories): a donation-safety audit (a donated run_fn/step_fn
  carry fed twice breaks only on TPU; the audit catches it on CPU), a
  hot-body purity audit (no host callbacks inside engine loop bodies),
  and a dtype-overflow audit for the uint32 cumulative counter ring.
* **Pipeline** (`report`, `__main__`): findings render as a TLC-style
  warnings banner, journal as schema-validated `analysis` events
  (obs/schema.py), and error severity exits nonzero.  `python -m
  jaxtlc.analysis MC.cfg` runs the suite standalone; `--self-check`
  audits every shipped engine factory.

Severities: ``error`` (the run would be wrong or die - preflight exits
nonzero), ``warning`` (the run proceeds but something will bite at
scale), ``info`` (report-only context).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

SEV_ERROR = "error"
SEV_WARNING = "warning"
SEV_INFO = "info"
_SEV_RANK = {SEV_INFO: 0, SEV_WARNING: 1, SEV_ERROR: 2}

# exit code of a preflight abort (TLC's EC convention reserves 10-13
# for spec-level verdicts; preflight failures are config/tooling errors)
EXIT_PREFLIGHT = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    """One preflight diagnostic: which layer/check fired, on what, why."""

    layer: str  # "spec" | "engine"
    check: str  # kebab-case check id, e.g. "invariant-vacuity"
    severity: str  # SEV_ERROR | SEV_WARNING | SEV_INFO
    subject: str  # the action/invariant/engine/counter concerned
    detail: str  # one human-readable sentence

    def as_event(self) -> dict:
        """The journal `analysis` event payload (obs/schema.py)."""
        return dict(layer=self.layer, check=self.check,
                    severity=self.severity, subject=self.subject,
                    detail=self.detail)


@dataclasses.dataclass
class AnalysisReport:
    """The preflight result: findings + the report sections that back
    them (rendered byte-stably by `report.render_report`)."""

    name: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    spec: Optional[object] = None  # speclint.SpecAnalysis
    engine_lines: List[str] = dataclasses.field(default_factory=list)
    # certified-bound report section (absint.BoundReport.render_lines);
    # empty on reports that did not run the abstract interpretation, so
    # pre-existing golden reports render byte-identically
    bound_lines: List[str] = dataclasses.field(default_factory=list)
    wall_s: float = 0.0

    def extend(self, findings) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings if f.severity == SEV_ERROR)

    @property
    def warnings(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings
                     if f.severity == SEV_WARNING)

    @property
    def max_severity(self) -> Optional[str]:
        if not self.findings:
            return None
        return max((f.severity for f in self.findings),
                   key=_SEV_RANK.__getitem__)

    @property
    def exit_code(self) -> int:
        """Nonzero iff an error-severity finding survived."""
        return EXIT_PREFLIGHT if self.errors else 0


def sorted_findings(findings) -> List[Finding]:
    """Deterministic order: severity (errors first), layer, check,
    subject - the rendering and journaling order."""
    return sorted(
        findings,
        key=lambda f: (-_SEV_RANK[f.severity], f.layer, f.check,
                       f.subject),
    )


from .report import emit_to_journal, render_banner, render_report  # noqa: E402,F401
