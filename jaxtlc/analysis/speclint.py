"""Spec-layer lints over the struct frontend's IR (E1 preflight).

Works on exactly what the LaneCompiler consumes - the parsed module
ASTs (struct.parser), the MC.cfg-resolved constants (struct.loader),
the inferred shapes (struct.shapes) and the codec layout
(struct.codec) - WITHOUT building a step function or touching XLA, so
the whole pass is milliseconds of host Python:

* **Action decomposition** mirrors the lane walker's label attribution
  (struct/compile.py `_walk_seq` / struct/actions.py `_enum`): the
  innermost expanded non-disjunction definition names the action, `\\/`
  and action-position `\\E` fork branches, `var' = e` / `var' \\in S`
  are writes, everything else is a guard.
* **Read/write sets** per action: a variable is READ when its
  pre-state value is mentioned (through any definition expansion),
  WRITTEN when primed-assigned.  UNCHANGED vars are identity updates -
  neither (identity commutes with everything).  These sets are the
  groundwork for the ROADMAP #5 invariant-inference direction: two
  actions are *independent* when neither writes what the other touches
  (the classic partial-order-reduction condition).
* **Unreachable actions**: a guard conjunct that mentions no state
  variable and no binder evaluates at preflight under the MC.cfg
  constant overrides (TLC's level-0 constant evaluation); FALSE on
  every branch means the action can never fire.
* **Invariant vacuity**: an INVARIANT that reads no state variable is
  checking nothing about the run.
* **Slot/trap budget**: an action-position `\\E x \\in S` over a
  STATE-DEPENDENT set compiles to SLOT_CAP k-th-set-bit lanes when the
  element universe exceeds UNROLL_LIMIT; a reachable state whose set
  grows past SLOT_CAP then halts the device run with
  VIOL_SLOT_OVERFLOW.  The audit bounds the universe statically and
  names the action up front.  Dynamic sequence reads (`s[expr]`) are
  reported as trap sites, with their IF/CASE branch gating noted - the
  RaftReplication false-trap class (PERF.md round 7) as a line in a
  report instead of a dead device run.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from ..struct.codec import StructCodec
from ..struct.parser import Definition
from ..struct.shapes import (
    SSeq,
    SUnion,
    ShapeError,
    ShapeInference,
    infer_shapes,
    typeok_hints,
    universe,
)
from . import SEV_WARNING, Finding

# the LaneCompiler's fan-out constants (struct/compile.py); imported
# rather than duplicated so the audit can never drift from the compiler
from ..struct.compile import SLOT_CAP, UNROLL_LIMIT


@dataclasses.dataclass
class ActionInfo:
    """Static summary of one named action across all its branches."""

    name: str
    reads: Set[str] = dataclasses.field(default_factory=set)
    writes: Set[str] = dataclasses.field(default_factory=set)
    unchanged: Set[str] = dataclasses.field(default_factory=set)
    n_branches: int = 0
    n_disabled: int = 0  # branches with a statically-FALSE guard
    slot_binders: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list
    )  # (binder name, element-universe size) on the mask/slot path
    seq_reads: int = 0  # dynamic sequence index sites
    gated_seq_reads: int = 0  # of those, inside an IF/CASE branch


@dataclasses.dataclass
class SpecAnalysis:
    root: str
    variables: Tuple[str, ...]
    n_fields: int  # codec lanes per state vector
    actions: Dict[str, ActionInfo]
    invariant_reads: Dict[str, Set[str]]
    independent_pairs: List[Tuple[str, str]]
    findings: List[Finding]


# ---------------------------------------------------------------------------
# Free state-variable reads (with definition expansion)
# ---------------------------------------------------------------------------


def _state_reads(ast, variables, defs, bound, out: Set[str],
                 seen: Optional[Set[str]] = None) -> None:
    """Collect state variables whose PRE-state value `ast` mentions.
    Primed mentions are not pre-state reads (ordered processing: a
    primed read follows its own assignment, struct/actions.py docstring);
    UNCHANGED contributes nothing (identity)."""
    if seen is None:
        seen = set()
    stack = [(ast, frozenset(bound))]
    while stack:
        node, bnd = stack.pop()
        if isinstance(node, list):
            stack.extend((x, bnd) for x in node)
            continue
        if not isinstance(node, tuple) or not node:
            continue
        op = node[0]
        if op in ("prime", "unchanged"):
            continue
        if op == "name" and len(node) == 2 and isinstance(node[1], str):
            nm = node[1]
            if nm in bnd:
                continue
            if nm in variables:
                out.add(nm)
                continue
            d = defs.get(nm)
            if d is not None and not d.params and nm not in seen:
                seen.add(nm)
                stack.append((d.body, bnd))
            continue
        if op == "call" and len(node) == 3 and isinstance(node[1], str):
            nm = node[1]
            d = defs.get(nm)
            stack.extend((a, bnd) for a in node[2])
            if d is not None and nm not in seen:
                seen.add(nm)
                stack.append((d.body, bnd | frozenset(d.params)))
            continue
        if op in ("exists", "forall") and len(node) == 4:
            _, names, dom_ast, body = node
            stack.append((dom_ast, bnd))
            stack.append((body, bnd | frozenset(names)))
            continue
        if op in ("setfilter", "choose") and len(node) == 4:
            _, var, dom_ast, body = node
            stack.append((dom_ast, bnd))
            stack.append((body, bnd | {var}))
            continue
        if op == "setmap" and len(node) == 4:
            _, expr, var, dom_ast = node
            stack.append((dom_ast, bnd))
            stack.append((expr, bnd | {var}))
            continue
        if op == "fnlit" and len(node) == 4:
            _, var, dom_ast, body = node
            stack.append((dom_ast, bnd))
            stack.append((body, bnd | {var}))
            continue
        if op == "let" and len(node) == 3 and isinstance(node[1], list):
            b2 = bnd
            for name, params, body in node[1]:
                stack.append((body, b2 | frozenset(params)))
                b2 = b2 | {name}
            stack.append((node[2], b2))
            continue
        # generic node; when the head is not an op string (record
        # fields, EXCEPT path groups), the first element is data too
        start = 1 if isinstance(op, str) else 0
        stack.extend((x, bnd) for x in node[start:]
                     if isinstance(x, (tuple, list)))


def _mentions_any(ast, names: Set[str], defs, seen=None) -> bool:
    """True when `ast` mentions any of `names` as a bare name (through
    definition expansion), or mentions a prime/UNCHANGED - used to
    classify guards as binder- or state-dependent."""
    if seen is None:
        seen = set()
    stack = [ast]
    while stack:
        node = stack.pop()
        if isinstance(node, list):
            stack.extend(node)
            continue
        if not isinstance(node, tuple) or not node:
            continue
        op = node[0]
        if op in ("prime", "unchanged"):
            return True  # primed mention: not constant-evaluable
        if op in ("name", "call") and len(node) >= 2 \
                and isinstance(node[1], str):
            nm = node[1]
            if nm in names:
                return True
            d = defs.get(nm)
            if d is not None and nm not in seen:
                seen.add(nm)
                stack.append(d.body)
            if op == "call":
                stack.extend(x for x in node[2]
                             if isinstance(x, (tuple, list)))
            continue
        start = 1 if isinstance(op, str) else 0
        stack.extend(x for x in node[start:]
                     if isinstance(x, (tuple, list)))
    return False


# ---------------------------------------------------------------------------
# Action decomposition (syntactic mirror of the lane walker)
# ---------------------------------------------------------------------------


class _Branch:
    __slots__ = ("bound", "guards", "writes", "unchanged", "reads",
                 "slot_binders", "seq_reads", "gated_seq_reads",
                 "disabled", "senv")

    def __init__(self):
        self.bound: Set[str] = set()
        self.guards: List[tuple] = []
        self.writes: Set[str] = set()
        self.unchanged: Set[str] = set()
        self.reads: Set[str] = set()
        self.slot_binders: List[Tuple[str, int]] = []
        self.seq_reads = 0
        self.gated_seq_reads = 0
        self.disabled = False
        # binder/param name -> inferred Shape (or Definition), so the
        # shape oracle can classify expressions UNDER the binders (the
        # RaftReplication trap sits inside LastTerm(log[i]))
        self.senv: dict = {}

    def fork(self) -> "_Branch":
        b = _Branch()
        b.bound = set(self.bound)
        b.guards = list(self.guards)
        b.writes = set(self.writes)
        b.unchanged = set(self.unchanged)
        b.reads = set(self.reads)
        b.slot_binders = list(self.slot_binders)
        b.seq_reads = self.seq_reads
        b.gated_seq_reads = self.gated_seq_reads
        b.disabled = self.disabled
        b.senv = dict(self.senv)
        return b


class _SpecWalker:
    def __init__(self, model, var_shapes,
                 dynamic_consts=frozenset(), const_hints=None):
        self.model = model
        self.system = model.system
        self.ev = self.system.ev
        self.variables = set(self.system.variables)
        self.defs = self.ev.defs
        self.var_shapes = var_shapes
        # constants swept over a range (jaxtlc.analysis --sweep): not
        # constant-evaluable - guards mentioning them are classified
        # like state-dependent ones, so the class audit never calls an
        # action unreachable on the strength of ONE configuration
        self.dynamic_consts = frozenset(dynamic_consts)
        # shape oracle for quantifier-domain classification: reuse the
        # compiler's own abstract interpreter over the final shapes
        self._inf = ShapeInference.__new__(ShapeInference)
        self._inf.ev = self.ev
        self._inf.variables = self.system.variables
        self._inf.var_shapes = dict(var_shapes)
        if const_hints:
            self._inf.const_hints = dict(const_hints)
        self.branches: Dict[str, List[_Branch]] = {}

    # -- helpers -----------------------------------------------------------

    def _reads(self, ast, br: _Branch) -> None:
        _state_reads(ast, self.variables, self.defs, br.bound, br.reads)

    def _shape_env(self, br: _Branch) -> dict:
        env = {v: s for v, s in self.var_shapes.items()}
        env.update(br.senv)
        return env

    def _abs(self, ast, env):
        """Best-effort shape of `ast` under `env` via the compiler's
        abstract interpreter; None when it cannot be bounded."""
        try:
            return self._inf._abstract(ast, env)
        except (ShapeError, KeyError, TypeError, ValueError,
                RecursionError):
            return None

    def _dom_universe(self, dom_ast, br: _Branch) -> Optional[int]:
        """Element-universe size of a quantifier domain, or None when
        the shape oracle cannot bound it."""
        sh = self._abs(dom_ast, self._shape_env(br))
        if sh is None:
            return None
        elem = self._inf._elem_shape(sh)
        if elem is None:
            return None
        try:
            return len(universe(elem, 1 << 16))
        except ShapeError:
            return None

    def _audit_traps(self, ast, br: _Branch, gated: bool, env,
                     seen: Optional[frozenset] = None) -> None:
        """Count dynamic sequence reads (`s[expr]`, expr non-literal)
        and whether they sit inside an IF/CASE branch - where the
        compiler gates their trap effect by the branch condition, the
        RaftReplication false-trap fix (PERF.md round 7).  Definitions
        expand with their parameter shapes bound (LastTerm(log[i])'s
        `s[Len(s)]` is a seq read only once `s`'s shape is known), once
        per path (cycle-guarded)."""
        if seen is None:
            seen = frozenset()
        if isinstance(ast, list):
            for x in ast:
                self._audit_traps(x, br, gated, env, seen)
            return
        if not isinstance(ast, tuple) or not ast:
            return
        op = ast[0]
        if op == "apply" and len(ast) == 3 and isinstance(ast[2], tuple) \
                and ast[2][0] not in ("str", "num"):
            sh = self._abs(ast[1], env)
            if isinstance(sh, SSeq) or (
                isinstance(sh, SUnion)
                and any(isinstance(a, SSeq) for a in sh.alts)
            ):
                br.seq_reads += 1
                if gated:
                    br.gated_seq_reads += 1
        if op in ("name", "call") and len(ast) >= 2 \
                and isinstance(ast[1], str):
            d = env.get(ast[1])
            if not isinstance(d, Definition):
                d = self.defs.get(ast[1])
            if isinstance(d, Definition) and ast[1] not in seen:
                env2 = dict(env)
                if op == "call" and len(ast) == 3:
                    for p, a in zip(d.params, ast[2]):
                        env2[p] = self._abs(a, env)
                self._audit_traps(d.body, br, gated, env2,
                                  seen | {ast[1]})
            if op == "call" and len(ast) == 3:
                for a in ast[2]:
                    self._audit_traps(a, br, gated, env, seen)
            return
        if op in ("exists", "forall", "setfilter", "choose") \
                and len(ast) == 4:
            names = ast[1] if op in ("exists", "forall") else (ast[1],)
            if isinstance(names, str):
                names = (names,)
            dom_ast, body = ast[2], ast[3]
            self._audit_traps(dom_ast, br, gated, env, seen)
            elem = self._inf._elem_shape(self._abs(dom_ast, env))
            env2 = dict(env)
            for nm in names:
                env2[nm] = elem
            self._audit_traps(body, br, gated, env2, seen)
            return
        if op == "let" and len(ast) == 3 and isinstance(ast[1], list):
            env2 = dict(env)
            for name, params, body in ast[1]:
                self._audit_traps(body, br, gated, env2, seen)
                env2[name] = (Definition(name, params, body) if params
                              else self._abs(body, env2))
            self._audit_traps(ast[2], br, gated, env2, seen)
            return
        if op == "if" and len(ast) == 4:
            self._audit_traps(ast[1], br, gated, env, seen)
            self._audit_traps(ast[2], br, True, env, seen)
            self._audit_traps(ast[3], br, True, env, seen)
            return
        inner_gated = gated or op == "case"
        start = 1 if isinstance(op, str) else 0
        for x in ast[start:]:
            if isinstance(x, (tuple, list)):
                self._audit_traps(x, br, inner_gated, env, seen)

    def _guard_static_false(self, g, br: _Branch) -> bool:
        """True when guard `g` is constant-evaluable (no state vars, no
        binders, no primes, no swept constants) and evaluates FALSE
        under the resolved constants - TLC's level-0 constant
        evaluation."""
        if _mentions_any(g, self.variables | br.bound
                         | self.dynamic_consts, self.defs):
            return False
        try:
            v = self.ev.eval(g, dict(self.ev.constants))
        except Exception:
            return False
        return v is False

    # -- walk --------------------------------------------------------------

    def walk(self) -> None:
        self._seq([self.system.next_ast], 0, _Branch(), None)

    def _done(self, br: _Branch, label: Optional[str]) -> None:
        self.branches.setdefault(label or "?", []).append(br)

    def _seq(self, items, i, br: _Branch, label) -> None:
        if i == len(items):
            self._done(br, label)
            return
        ast = items[i]
        rest = items[i + 1:]
        op = ast[0]
        if op == "and":
            self._seq(list(ast[1]) + rest, 0, br, label)
            return
        if op == "or":
            for branch in ast[1]:
                self._seq([branch] + rest, 0, br.fork(), label)
            return
        if op == "exists":
            _, names, dom_ast, body = ast
            self._reads(dom_ast, br)
            b2 = br.fork()
            b2.bound |= set(names)
            elem = self._inf._elem_shape(
                self._abs(dom_ast, self._shape_env(br))
            )
            for nm in names:
                b2.senv[nm] = elem
            state_dep = _mentions_any(
                dom_ast, self.variables | br.bound, self.defs
            )
            if state_dep:
                u = self._dom_universe(dom_ast, br)
                if u is not None and u > UNROLL_LIMIT:
                    # the mask path: SLOT_CAP k-th-set-bit slot lanes
                    for nm in names:
                        b2.slot_binders.append((nm, u))
            self._seq([body] + rest, 0, b2, label)
            return
        if op == "if":
            self._reads(ast[1], br)
            self._audit_traps(ast[1], br, False, self._shape_env(br))
            for arm in (ast[2], ast[3]):
                self._seq([arm] + rest, 0, br.fork(), label)
            return
        if op == "let":
            b2 = br.fork()
            for name, params, body in ast[1]:
                self._reads(body, br)
                b2.bound.add(name)
                b2.senv[name] = (
                    Definition(name, params, body) if params
                    else self._abs(body, self._shape_env(b2))
                )
            self._seq([ast[2]] + rest, 0, b2, label)
            return
        if op in ("call", "name"):
            dname = ast[1]
            d = self.defs.get(dname)
            if d is not None and self.system._mentions_prime(d.body):
                args = ast[2] if op == "call" else []
                for a in args:
                    self._reads(a, br)
                b2 = br.fork()
                b2.bound |= set(d.params)
                env = self._shape_env(br)
                for p, a in zip(d.params, args):
                    b2.senv[p] = self._abs(a, env)
                inner = label if d.body[0] == "or" else dname
                self._seq([d.body] + rest, 0, b2, inner)
                return
        if op == "unchanged":
            b2 = br.fork()
            b2.unchanged |= set(ast[1])
            self._seq(rest, 0, b2, label)
            return
        if op == "cmp" and ast[1] in ("=", r"\in") \
                and ast[2][0] == "prime":
            b2 = br.fork()
            b2.writes.add(ast[2][1])
            self._reads(ast[3], b2)
            self._audit_traps(ast[3], b2, False, self._shape_env(b2))
            self._seq(rest, 0, b2, label)
            return
        # plain guard conjunct
        b2 = br.fork()
        b2.guards.append(ast)
        self._reads(ast, b2)
        self._audit_traps(ast, b2, False, self._shape_env(b2))
        if self._guard_static_false(ast, b2):
            b2.disabled = True
        self._seq(rest, 0, b2, label)


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def analyze_spec(model, var_shapes: Optional[dict] = None,
                 dynamic_consts=frozenset(),
                 const_hints=None) -> SpecAnalysis:
    """Run the spec-layer lints on a loaded StructModel.  `var_shapes`
    reuses already-inferred shapes (the struct backend memo computes
    them anyway); omitted, the same pure-Python inference runs here.
    `dynamic_consts` names CONSTANTs swept over a range and
    `const_hints` widens them to abstract values, so one pass audits a
    whole sweep constants class instead of its anchor configuration."""
    system = model.system
    if var_shapes is None:
        hints = typeok_hints(system.ev, model.invariants,
                             system.variables)
        var_shapes = infer_shapes(system.ev, system.variables,
                                  system.init_ast, system.next_ast,
                                  hints=hints, const_hints=const_hints)
    cdc = StructCodec(system.variables, var_shapes)

    w = _SpecWalker(model, var_shapes, dynamic_consts=dynamic_consts,
                    const_hints=const_hints)
    w.walk()

    actions: Dict[str, ActionInfo] = {}
    for label in sorted(w.branches):
        info = ActionInfo(name=label)
        for br in w.branches[label]:
            info.n_branches += 1
            if br.disabled:
                info.n_disabled += 1
            info.reads |= br.reads
            info.writes |= br.writes
            info.unchanged |= br.unchanged
            info.slot_binders.extend(
                b for b in br.slot_binders
                if b not in info.slot_binders
            )
            info.seq_reads = max(info.seq_reads, br.seq_reads)
            info.gated_seq_reads = max(info.gated_seq_reads,
                                       br.gated_seq_reads)
        actions[label] = info

    findings: List[Finding] = []
    for label, info in actions.items():
        if info.n_branches and info.n_disabled == info.n_branches:
            findings.append(Finding(
                layer="spec", check="unreachable-action",
                severity=SEV_WARNING, subject=label,
                detail=(f"every branch of {label} has a guard that is "
                        "statically FALSE under the resolved constants; "
                        "the action can never fire"),
            ))
        for nm, u in info.slot_binders:
            findings.append(Finding(
                layer="spec", check="slot-budget",
                severity=SEV_WARNING, subject=label,
                detail=(f"\\E {nm} picks from a state-dependent set of "
                        f"up to {u} elements through {SLOT_CAP} slot "
                        f"lanes (universe {u} > unroll limit "
                        f"{UNROLL_LIMIT}); a reachable state whose set "
                        f"exceeds {SLOT_CAP} halts with "
                        "VIOL_SLOT_OVERFLOW"),
            ))

    inv_reads: Dict[str, Set[str]] = {}
    for name, ast in model.invariants.items():
        reads: Set[str] = set()
        _state_reads(ast, w.variables, w.defs, set(), reads)
        inv_reads[name] = reads
        if not reads:
            findings.append(Finding(
                layer="spec", check="invariant-vacuity",
                severity=SEV_WARNING, subject=name,
                detail=(f"invariant {name} reads no state variable; it "
                        "constrains nothing about the run"),
            ))

    names = sorted(actions)
    pairs: List[Tuple[str, str]] = []
    for i, a in enumerate(names):
        for b in names[i + 1:]:
            ia, ib = actions[a], actions[b]
            if not (ia.writes & (ib.reads | ib.writes)) and \
                    not (ib.writes & (ia.reads | ia.writes)):
                pairs.append((a, b))

    return SpecAnalysis(
        root=model.root_name,
        variables=system.variables,
        n_fields=cdc.n_fields,
        actions=actions,
        invariant_reads=inv_reads,
        independent_pairs=pairs,
        findings=findings,
    )
