"""Symmetry-set detection + POR ample-action analysis (ISSUE 18).

Static, engine-free verification of the two state-space reductions
`engine.reduce` applies at expand time:

* **Symmetric constant sets** - the TLC ``SYMMETRY`` condition: a
  CONSTANT resolved to a set of model values (atoms) whose elements the
  spec only ever compares for equality.  In this IR that is checkable
  syntactically: atoms are plain strings, and the only way a spec can
  distinguish two atoms of a set S is (a) naming one as a string
  literal, (b) pinning one through ANOTHER constant whose value embeds
  it, or (c) ``CHOOSE`` (whose deterministic pick is not
  permutation-equivariant).  A candidate passing all three checks is
  permutation-symmetric: for every permutation pi of S and reachable
  state s, pi(s) is reachable, and every invariant/property satisfies
  Inv(pi(s)) = Inv(s) - the soundness basis for fingerprinting only
  orbit representatives.

* **POR-safe actions** - singleton ample sets.  An action A may be the
  sole expansion of a state where it is enabled when (1) A is
  *independent* of every other action (speclint's read/write condition,
  `SpecAnalysis.independent_pairs` - so executing others neither
  disables A nor changes what A does, and vice versa), (2) A is
  *invisible* - writes(A) touches no variable any INVARIANT reads, so
  commuting A across other actions never changes an invariant verdict,
  and (3) the *cycle condition* holds: A strictly increments a counter
  variable (``v' = v + c``, c >= 1, in every branch) that, by (1), no
  other action writes - so no cycle of the reduced graph consists of
  ample steps only, and nothing is postponed forever.  Deadlocks are
  preserved separately by the engine: the deadlock test runs on the
  pre-pruning successor mask.

Everything here is host Python over the parsed ASTs and resolved
constants - the same surface speclint audits - so
``python -m jaxtlc.analysis --por-report MC.cfg`` can print the whole
reduction story without building a step function.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Set, Tuple

from . import SEV_WARNING, Finding
from .speclint import SpecAnalysis, analyze_spec

# orbit-group budget: the canonicalization kernel unrolls one field
# program per non-identity permutation, so the product of |S|! over the
# kept sets is capped (TLC warns past small symmetry groups for the
# same reason - canonicalization cost grows factorially)
PERM_LIMIT = 24


# ---------------------------------------------------------------------------
# Symmetric constant sets
# ---------------------------------------------------------------------------


def _spec_atom_surface(model) -> Tuple[Set[str], bool]:
    """(string literals, CHOOSE reachable?) over the reachable-def
    closure of init/next/invariants/properties - the full surface a
    permutation of constant atoms must commute with."""
    system = model.system
    defs = system.ev.defs
    strs: Set[str] = set()
    has_choose = False
    stack: List[object] = [system.init_ast, system.next_ast]
    stack.extend(model.invariants.values())
    props = getattr(model, "properties", None) or {}
    if isinstance(props, dict):
        stack.extend(props.values())
    seen: Set[str] = set()
    while stack:
        node = stack.pop()
        if isinstance(node, list):
            stack.extend(node)
            continue
        if not isinstance(node, tuple) or not node:
            continue
        op = node[0]
        if op == "str" and len(node) == 2 and isinstance(node[1], str):
            strs.add(node[1])
            continue
        if op == "choose":
            has_choose = True
        if op in ("name", "call") and len(node) >= 2 \
                and isinstance(node[1], str):
            d = defs.get(node[1])
            if d is not None and node[1] not in seen:
                seen.add(node[1])
                stack.append(d.body)
            if op == "call" and len(node) == 3:
                stack.extend(x for x in node[2]
                             if isinstance(x, (tuple, list)))
            continue
        start = 1 if isinstance(op, str) else 0
        stack.extend(x for x in node[start:]
                     if isinstance(x, (tuple, list)))
    return strs, has_choose


def _atoms_in(value, out: Set[str]) -> None:
    if isinstance(value, str):
        out.add(value)
    elif isinstance(value, frozenset):
        for x in value:
            _atoms_in(x, out)
    elif isinstance(value, tuple):
        for x in value:
            _atoms_in(x, out)


def find_symmetric_sets(model) -> Tuple[
        Dict[str, Tuple[str, ...]], Dict[str, str]]:
    """(kept, rejected): candidate symmetric sets are CONSTANTs resolved
    to frozensets of >= 2 atoms; `kept` maps constant name -> sorted
    atom tuple for the sets that pass static verification, `rejected`
    maps the rest to a human-readable reason."""
    candidates = {
        name: v for name, v in sorted(model.constants.items())
        if isinstance(v, frozenset) and len(v) >= 2
        and all(isinstance(x, str) for x in v)
    }
    kept: Dict[str, Tuple[str, ...]] = {}
    rejected: Dict[str, str] = {}
    if not candidates:
        return kept, rejected
    strs, has_choose = _spec_atom_surface(model)
    budget = 1
    for name, val in candidates.items():
        atoms = tuple(sorted(val))
        why: Optional[str] = None
        if has_choose:
            why = ("spec reaches a CHOOSE; its deterministic pick is "
                   "not permutation-equivariant")
        if why is None:
            hit = sorted(set(atoms) & strs)
            if hit:
                why = (f"element(s) {', '.join(hit)} appear as string "
                       "literals in the spec")
        if why is None:
            for other, oval in sorted(model.constants.items()):
                if other == name or oval == val:
                    continue
                used: Set[str] = set()
                _atoms_in(oval, used)
                pin = sorted(set(atoms) & used)
                if pin:
                    why = (f"element(s) {', '.join(pin)} are pinned "
                           f"through constant {other}")
                    break
        if why is None:
            fact = math.factorial(len(atoms))
            if budget * fact > PERM_LIMIT:
                why = (f"orbit-group budget: |{name}|! = {fact} would "
                       f"push the group past {PERM_LIMIT} permutations")
            else:
                budget *= fact
                kept[name] = atoms
                continue
        rejected[name] = why
    return kept, rejected


def unreduced_symmetry_findings(model) -> List[Finding]:
    """One SEV_WARNING per SYMMETRY-eligible set: the spec qualifies
    for orbit dedup but the run is not taking it (preflight journals
    these; a `-symmetry` run drops the reduced sets from the list the
    struct backend leaves over)."""
    kept, _rejected = find_symmetric_sets(model)
    out: List[Finding] = []
    for name, atoms in kept.items():
        out.append(Finding(
            layer="spec", check="unreduced-symmetry",
            severity=SEV_WARNING, subject=name,
            detail=(f"constant {name} = {{{', '.join(atoms)}}} is "
                    "SYMMETRY-eligible (elements only ever "
                    "equality-compared); -symmetry dedups its "
                    f"{math.factorial(len(atoms))}-way orbits"),
        ))
    return out


# ---------------------------------------------------------------------------
# POR-safe actions (singleton ample sets)
# ---------------------------------------------------------------------------


def _is_increment(rhs, v: str) -> bool:
    """rhs is syntactically `v + c` or `c + v` with literal c >= 1."""
    if not (isinstance(rhs, tuple) and len(rhs) == 4
            and rhs[0] == "binop" and rhs[1] == "+"):
        return False
    for x, y in ((rhs[2], rhs[3]), (rhs[3], rhs[2])):
        if x == ("name", v) and isinstance(y, tuple) and len(y) == 2 \
                and y[0] == "num" and isinstance(y[1], int) and y[1] >= 1:
            return True
    return False


def _monotone_every_branch(ast, v: str, defs,
                           seen: frozenset = frozenset()) -> bool:
    """True when EVERY disjunctive branch of `ast` carries a conjunct
    `v' = v + c` (c >= 1 literal) - the strictly-monotone counter that
    discharges the POR cycle condition for the action owning `ast`."""
    if not isinstance(ast, tuple) or not ast:
        return False
    op = ast[0]
    if op == "and":
        return any(_monotone_every_branch(x, v, defs, seen)
                   for x in ast[1])
    if op == "or":
        return bool(ast[1]) and all(
            _monotone_every_branch(x, v, defs, seen) for x in ast[1]
        )
    if op == "exists" and len(ast) == 4:
        return _monotone_every_branch(ast[3], v, defs, seen)
    if op == "if" and len(ast) == 4:
        return (_monotone_every_branch(ast[2], v, defs, seen)
                and _monotone_every_branch(ast[3], v, defs, seen))
    if op == "let" and len(ast) == 3:
        return _monotone_every_branch(ast[2], v, defs, seen)
    if op in ("name", "call") and len(ast) >= 2 \
            and isinstance(ast[1], str):
        d = defs.get(ast[1])
        if d is not None and ast[1] not in seen:
            return _monotone_every_branch(d.body, v, defs,
                                          seen | {ast[1]})
        return False
    if op == "cmp" and len(ast) == 4 and ast[1] == "=" \
            and ast[2] == ("prime", v):
        return _is_increment(ast[3], v)
    return False


def safe_por_actions(spec: SpecAnalysis, model) -> Tuple[
        Tuple[str, ...], Dict[str, str]]:
    """(safe, reasons): actions eligible as singleton ample sets, and
    why the rest are not.  `safe` is sorted by action name - the engine
    picks the LOWEST-id safe enabled action, and label ids are the
    sorted-name order, so the choice is deterministic across runs."""
    defs = model.system.ev.defs
    inv_reads: Set[str] = set()
    for reads in spec.invariant_reads.values():
        inv_reads |= reads
    indep = set(spec.independent_pairs)
    names = sorted(spec.actions)
    safe: List[str] = []
    reasons: Dict[str, str] = {}
    for a in names:
        info = spec.actions[a]
        deps = [b for b in names if b != a
                and (a, b) not in indep and (b, a) not in indep]
        if deps:
            shown = ", ".join(deps[:4]) + ("..." if len(deps) > 4 else "")
            reasons[a] = f"dependent on {shown}"
            continue
        vis = sorted(info.writes & inv_reads)
        if vis:
            reasons[a] = ("visible: writes invariant-read "
                          f"variable(s) {', '.join(vis)}")
            continue
        d = defs.get(a)
        mono = [v for v in sorted(info.writes)
                if d is not None
                and _monotone_every_branch(d.body, v, defs)]
        if not mono:
            reasons[a] = ("no strictly-monotone counter write "
                          "(v' = v + c, c >= 1, in every branch) to "
                          "discharge the cycle condition")
            continue
        safe.append(a)
    return tuple(safe), reasons


# ---------------------------------------------------------------------------
# Combined report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SymReport:
    """Everything the struct backend, the `--por-report` renderer and
    preflight need about one model's reduction opportunities."""

    symmetric_sets: Dict[str, Tuple[str, ...]]
    rejected_sets: Dict[str, str]
    safe_actions: Tuple[str, ...]
    unsafe_actions: Dict[str, str]
    spec: SpecAnalysis

    @property
    def orbit_factor(self) -> int:
        f = 1
        for atoms in self.symmetric_sets.values():
            f *= math.factorial(len(atoms))
        return f


def analyze_reduction(model,
                      spec: Optional[SpecAnalysis] = None) -> SymReport:
    if spec is None:
        spec = analyze_spec(model)
    kept, rejected = find_symmetric_sets(model)
    safe, unsafe = safe_por_actions(spec, model)
    return SymReport(
        symmetric_sets=kept, rejected_sets=rejected,
        safe_actions=safe, unsafe_actions=unsafe, spec=spec,
    )


def render_por_report(model,
                      spec: Optional[SpecAnalysis] = None) -> str:
    """Engine-free text report: the independence graph, per-action
    ample eligibility with reasons, and the detected symmetric sets."""
    rep = analyze_reduction(model, spec)
    spec = rep.spec
    lines: List[str] = []
    lines.append(f"reduction report: {spec.root} "
                 f"({len(spec.actions)} actions, "
                 f"{spec.n_fields} codec fields)")
    lines.append("")
    lines.append("symmetric constant sets:")
    if not rep.symmetric_sets and not rep.rejected_sets:
        lines.append("  (no constant resolves to a set of >= 2 atoms)")
    for name, atoms in rep.symmetric_sets.items():
        lines.append(
            f"  {name} = {{{', '.join(atoms)}}}  SYMMETRY-eligible "
            f"({math.factorial(len(atoms))} orbit permutations)"
        )
    for name, why in rep.rejected_sets.items():
        lines.append(f"  {name}: not eligible - {why}")
    lines.append("")
    lines.append(f"independent action pairs "
                 f"({len(spec.independent_pairs)}):")
    if not spec.independent_pairs:
        lines.append("  (none)")
    for a, b in spec.independent_pairs:
        lines.append(f"  {a} || {b}")
    lines.append("")
    lines.append("ample-set eligibility (singleton ample):")
    for a in sorted(spec.actions):
        if a in rep.safe_actions:
            lines.append(f"  {a}: SAFE (independent of all, invisible, "
                         "monotone counter)")
        else:
            lines.append(f"  {a}: {rep.unsafe_actions.get(a, '?')}")
    return "\n".join(lines)
