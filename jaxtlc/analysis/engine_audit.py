"""Engine-layer audits over jaxprs traced from the engine factories.

Three hazards the device engines can carry silently on CPU and pay for
on TPU or at scale; each is checkable by tracing (never compiling) the
factory's run/step functions:

* **Donation safety**: `make_backend_engine(donate=True)` marks the
  carry donated so XLA aliases the ping-pong buffers.  Feeding the SAME
  carry twice (the supervisor retry loop, profilers, A/B harnesses) is
  then a use-after-donate - invisible on CPU where XLA has no donation,
  a garbage run on TPU.  The factories tag their functions with
  `donate_requested` / `donates_carry`; the audit cross-checks the tag
  against the driver's declared reuse.  (`JAXTLC_DEBUG_DONATION=1`
  additionally poisons donated carries at runtime so reuse fails fast
  on CPU too - analysis.donation.)
* **Hot-body purity**: a `pure_callback` / `io_callback` /
  `debug_callback` inside a `lax.while_loop` engine body syncs the
  device to the host EVERY iteration - the exact round-trip the fused
  engines exist to avoid.  The audit walks the traced jaxpr (through
  pjit / while / cond / scan sub-jaxprs) and flags any callback
  primitive.
* **Counter width**: the obs ring and per-action counters are
  cumulative uint32 (obs/counters.py).  `generated` grows up to
  n_lanes candidates per expanded state, so a run bounded by
  fp_capacity distinct states can generate up to fp_capacity * n_lanes
  - past 2^32 the columns silently wrap.  The audit flags the
  configuration up front; the ring's sticky overflow column
  (COL_OVERFLOW) catches the residual risk at runtime.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set

from . import SEV_ERROR, SEV_WARNING, Finding

U32_MAX = 1 << 32

# host-callback primitives that have no place in a fused engine body
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "host_callback_call", "outside_call",
})


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------


def _sub_jaxprs(params: dict):
    import jax.core as jc

    for v in params.values():
        if isinstance(v, jc.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jc.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, jc.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, jc.Jaxpr):
                    yield x


def jaxpr_primitives(jaxpr) -> Set[str]:
    """All primitive names in `jaxpr`, recursing through pjit bodies,
    while/cond/scan sub-jaxprs and custom-call wrappers."""
    prims: Set[str] = set()
    stack = [jaxpr]
    seen = set()
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            prims.add(eqn.primitive.name)
            stack.extend(_sub_jaxprs(eqn.params))
    return prims


def trace_engine_fn(fn, carry) -> Set[str]:
    """Primitive-name set of `fn(carry)` - tracing only, no XLA compile
    (the preflight contract: no extra engine compiles)."""
    import jax

    return jaxpr_primitives(jax.make_jaxpr(fn)(carry).jaxpr)


def carry_shapes(init_fn):
    """Abstract carry for tracing: `jax.eval_shape` when the init is
    traceable (single-device engines), the tiny concrete carry
    otherwise (the sharded init stages numpy through device_put)."""
    import jax

    try:
        return jax.eval_shape(init_fn)
    except Exception:
        return init_fn()


# ---------------------------------------------------------------------------
# audits
# ---------------------------------------------------------------------------


def audit_purity(name: str, fn, carry) -> List[Finding]:
    """Flag host-callback primitives inside an engine function body."""
    prims = trace_engine_fn(fn, carry)
    bad = sorted(prims & CALLBACK_PRIMS)
    if not bad:
        return []
    return [Finding(
        layer="engine", check="hot-body-purity", severity=SEV_ERROR,
        subject=name,
        detail=(f"{name} traces host callback primitive(s) "
                f"{', '.join(bad)} inside its device body; every loop "
                "iteration would sync to the host"),
    )]


def audit_donation(name: str, fn, reuses_carry: bool) -> List[Finding]:
    """Cross-check a factory function's donation tag against the
    driver's carry-reuse behavior.  `donate_requested` is the factory
    intent; on CPU XLA ignores donation (`donates_carry` False), which
    is exactly why the hazard must be flagged statically - the failure
    only reproduces on device."""
    requested = bool(getattr(fn, "donate_requested", False))
    if requested and reuses_carry:
        return [Finding(
            layer="engine", check="donation-reuse", severity=SEV_ERROR,
            subject=name,
            detail=(f"{name} donates its carry but the driver feeds the "
                    "same carry twice (retry/profiler reuse); on TPU "
                    "this is a use-after-donate - build the engine with "
                    "donate=False or stop reusing the carry"),
        )]
    return []


def audit_counter_width(subject: str, fp_capacity: int, n_lanes: int,
                        dtype_bits: int = 32) -> List[Finding]:
    """Static saturation bound for the cumulative uint32 counters: a
    run can expand up to fp_capacity distinct states, each generating
    up to n_lanes candidates, so cumulative `generated` (and the
    per-action columns summing to it) is bounded by fp_capacity *
    n_lanes.  Past 2^32 the uint32 columns wrap silently - exactly
    where ROADMAP #3's billion-state runs are headed.

    Note the bound assumes fp_capacity caps the distinct-state count.
    Once the HOST SPILL TIER activates (engine.spill - the recovery
    story for fpset saturation), distinct states are bounded by host
    RAM instead, so a spilling run can saturate these counters at ANY
    fp_capacity; the ring's sticky overflow column is then the only
    guard."""
    bound = int(fp_capacity) * max(int(n_lanes), 1)
    if bound < (1 << dtype_bits):
        return []
    return [Finding(
        layer="engine", check="counter-width", severity=SEV_WARNING,
        subject=subject,
        detail=(f"cumulative uint32 counters can saturate: fp_capacity "
                f"{fp_capacity} x {n_lanes} lanes bounds `generated` at "
                f"{bound} >= 2^{dtype_bits} (and the host spill tier, "
                "once active, lifts the fp_capacity bound entirely); "
                "the obs ring's sticky overflow column will flag it at "
                "runtime, but totals will be wrong - shard the fp "
                "space or lower fp_capacity"),
    )]


def audit_engine(
    name: str,
    init_fn=None,
    run_fn=None,
    step_fn=None,
    *,
    reuses_carry: bool = False,
    fp_capacity: Optional[int] = None,
    n_lanes: Optional[int] = None,
    trace: bool = True,
    carry=None,
) -> List[Finding]:
    """The full engine-layer suite over one factory's functions.
    `trace=False` skips the jaxpr purity pass (the CLI's lite preflight;
    `-analyze` and the self-check run it)."""
    findings: List[Finding] = []
    fns = [("run_fn", run_fn), ("step_fn", step_fn)]
    for label, fn in fns:
        if fn is None:
            continue
        findings.extend(audit_donation(f"{name}.{label}", fn,
                                       reuses_carry))
    if trace and init_fn is not None:
        if carry is None:
            carry = carry_shapes(init_fn)
        for label, fn in fns:
            if fn is None:
                continue
            findings.extend(audit_purity(f"{name}.{label}", fn, carry))
    if fp_capacity is not None and n_lanes is not None:
        findings.extend(audit_counter_width(name, fp_capacity, n_lanes))
    return findings


def describe_engine(name: str, fn, carry,
                    extras: Iterable[str] = ()) -> str:
    """One stable report line per audited engine function: primitive
    count + the capability-relevant primitive classes present (used by
    the golden engine-layer reports; primitive NAMES vary with jax
    versions less than their classes do)."""
    prims = trace_engine_fn(fn, carry)
    classes = []
    for label, members in (
        ("while", {"while"}),
        ("cond", {"cond"}),
        ("sort", {"sort"}),
        ("gather", {"gather", "dynamic_slice"}),
        # ragged_all_to_all / reduce_scatter are how newer jax lowers
        # the cross-host (DCN) exchange of a multi-process pod mesh
        # (jaxtlc.dist); they must classify as collective, not fall
        # through as unknown primitives, or the census would report a
        # pod engine as collective-free
        ("collective", {"all_to_all", "psum", "pmax", "pmin",
                        "all_gather", "ppermute", "ragged_all_to_all",
                        "reduce_scatter"}),
        ("callback", CALLBACK_PRIMS),
    ):
        if prims & members:
            classes.append(label)
    parts = [f"{name}: {'+'.join(classes)}"]
    parts.extend(extras)
    return "  ".join(parts)
