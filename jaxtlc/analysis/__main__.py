"""``python -m jaxtlc.analysis`` - the standalone preflight runner.

    python -m jaxtlc.analysis path/to/MC.cfg [--deep] [--journal PATH]
    python -m jaxtlc.analysis --self-check [--tiny]

The first form runs the preflight suite on a model (the same pass the
CLI runs before a check) and prints the full report; the second audits
every shipped engine factory (selfcheck.FACTORIES).  Exit status: 0
clean or warnings only, nonzero on error-severity findings.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m jaxtlc.analysis")
    p.add_argument("config", nargs="?", default="",
                   help="path to MC.cfg (preflight that model)")
    p.add_argument("--deep", action="store_true",
                   help="also trace the engine jaxpr (purity audit); "
                        "tracing only, never an XLA compile")
    p.add_argument("--journal", default="", metavar="PATH",
                   help="append the findings as schema-validated "
                        "`analysis` events to PATH")
    p.add_argument("--self-check", action="store_true",
                   dest="self_check",
                   help="audit every shipped engine factory (fused, "
                        "pipelined, sharded, struct, enumerator)")
    p.add_argument("--tiny", action="store_true",
                   help="tiny geometries (the tier-1 smoke mode)")
    args = p.parse_args(argv)

    if args.self_check:
        from .selfcheck import self_check

        report = self_check(tiny=args.tiny)
        _journal(args, report)
        if report.findings:
            from .report import print_report

            print_report(report)
        return report.exit_code

    if not args.config:
        p.print_usage(sys.stderr)
        print("error: an MC.cfg path or --self-check is required",
              file=sys.stderr)
        return 2

    from ..frontend.model import GenRunSpec, StructRunSpec, resolve

    try:
        spec = resolve(args.config)
    except (ValueError, OSError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2
    from .preflight import preflight_gen, preflight_kubeapi, preflight_struct
    from .report import print_report

    sizes = dict(fp_capacity=1 << 20, chunk=1024,
                 queue_capacity=1 << 15)
    if isinstance(spec, StructRunSpec):
        report = preflight_struct(
            spec.structmodel, deep=args.deep,
            check_deadlock=spec.check_deadlock, **sizes,
        )
    elif isinstance(spec, GenRunSpec):
        report = preflight_gen(spec.genspec,
                               fp_capacity=sizes["fp_capacity"],
                               deep=args.deep)
    else:
        report = preflight_kubeapi(spec.model, deep=args.deep, **sizes)
    print_report(report)
    _journal(args, report)
    return report.exit_code


def _journal(args, report) -> None:
    if not args.journal:
        return
    from ..obs.journal import RunJournal
    from .report import emit_to_journal

    with RunJournal(args.journal, resume=True) as j:
        emit_to_journal(j, report)


if __name__ == "__main__":
    sys.exit(main())
