"""``python -m jaxtlc.analysis`` - the standalone preflight runner.

    python -m jaxtlc.analysis path/to/MC.cfg [--deep] [--journal PATH]
                              [--sweep NAME=LO..HI]...
    python -m jaxtlc.analysis --self-check [--tiny]
    python -m jaxtlc.analysis --gate [SPECS_DIR]
    python -m jaxtlc.analysis --por-report path/to/MC.cfg

The first form runs the preflight suite on a model (the same pass the
CLI runs before a check) and prints the full report - ``--deep`` adds
the engine jaxpr trace AND the certified bound report, ``--sweep``
widens a swept integer CONSTANT to its whole lo..hi range so the
slot/trap budget audit and the bound report cover the sweep constants
CLASS instead of just the anchor configuration (the jaxtlc.serve sweep
contract).  The second audits every shipped engine factory
(selfcheck.FACTORIES).  The third runs the engine-free lint gate over
a specs tree (tools/lintgate.py's pass).  Exit status: 0 clean or
warnings only, nonzero on error-severity findings.
"""

from __future__ import annotations

import argparse
import sys


def _parse_sweep(items):
    """--sweep NAME=LO..HI descriptors -> {name: (lo, hi)}."""
    out = {}
    for it in items or ():
        try:
            name, rng = it.split("=", 1)
            lo, hi = rng.split("..", 1)
            out[name.strip()] = (int(lo), int(hi))
        except ValueError:
            raise SystemExit(
                f"error: bad --sweep descriptor {it!r} "
                "(want NAME=LO..HI, e.g. MAXR=1..3)"
            )
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m jaxtlc.analysis")
    p.add_argument("config", nargs="?", default="",
                   help="path to MC.cfg (preflight that model); with "
                        "--gate, a specs directory instead")
    p.add_argument("--deep", action="store_true",
                   help="also trace the engine jaxpr (purity audit; "
                        "tracing only, never an XLA compile) and "
                        "render the certified bound report")
    p.add_argument("--sweep", action="append", default=[],
                   metavar="NAME=LO..HI",
                   help="widen CONSTANT NAME over LO..HI so the audit "
                        "covers the whole sweep constants class, not "
                        "just the anchor configuration (repeatable)")
    p.add_argument("--journal", default="", metavar="PATH",
                   help="append the findings as schema-validated "
                        "`analysis` events to PATH")
    p.add_argument("--self-check", action="store_true",
                   dest="self_check",
                   help="audit every shipped engine factory (fused, "
                        "narrowed, pipelined, sharded, struct, "
                        "enumerator, ...)")
    p.add_argument("--gate", action="store_true",
                   help="engine-free lint gate: speclint + absint over "
                        "every MC.cfg under the given directory "
                        "(default specs/); nonzero on error findings")
    p.add_argument("--por-report", action="store_true",
                   dest="por_report",
                   help="engine-free state-space reduction report for "
                        "an MC.cfg: detected symmetric constant sets "
                        "(with rejection reasons), the action "
                        "independence graph, and per-action POR ample "
                        "eligibility - what -symmetry/-por would use")
    p.add_argument("--tiny", action="store_true",
                   help="tiny geometries (the tier-1 smoke mode)")
    args = p.parse_args(argv)

    if args.gate:
        import os

        from .gate import run_gate

        root = args.config or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "specs",
        )
        return run_gate(root)

    if args.self_check:
        from .selfcheck import self_check

        report = self_check(tiny=args.tiny)
        _journal(args, report)
        if report.findings:
            from .report import print_report

            print_report(report)
        return report.exit_code

    if not args.config:
        p.print_usage(sys.stderr)
        print("error: an MC.cfg path or --self-check is required",
              file=sys.stderr)
        return 2

    from ..frontend.model import GenRunSpec, StructRunSpec, resolve

    try:
        spec = resolve(args.config)
    except (ValueError, OSError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 2

    if args.por_report:
        # engine-free: pure IR analysis (speclint + symfind), no jax
        if not isinstance(spec, StructRunSpec):
            print("error: --por-report needs a struct-frontend spec",
                  file=sys.stderr)
            return 2
        from .symfind import render_por_report

        print(render_por_report(spec.structmodel))
        return 0
    from .preflight import preflight_gen, preflight_kubeapi, preflight_struct
    from .report import print_report

    sizes = dict(fp_capacity=1 << 20, chunk=1024,
                 queue_capacity=1 << 15)
    if args.sweep and not isinstance(spec, StructRunSpec):
        print("error: --sweep needs a struct-frontend spec",
              file=sys.stderr)
        return 2
    if isinstance(spec, StructRunSpec):
        sweep = _parse_sweep(args.sweep)
        const_hints = None
        extra_systems = ()
        if sweep:
            from ..struct.shapes import SInt

            sm = spec.structmodel
            const_hints = {n: SInt(lo, hi)
                           for n, (lo, hi) in sweep.items()}
            # each configuration's Init set seeds the bound env (the
            # anchor's initial states alone would under-approximate)
            extra_systems = []
            import itertools

            names = sorted(sweep)
            ranges = [range(sweep[n][0], sweep[n][1] + 1)
                      for n in names]
            for combo in itertools.product(*ranges):
                consts = dict(sm.constants)
                consts.update(dict(zip(names, combo)))
                extra_systems.append(
                    sm.system.with_constants(consts)
                )
        report = preflight_struct(
            spec.structmodel, deep=args.deep,
            check_deadlock=spec.check_deadlock,
            bounds=True if (args.deep or sweep) else None,
            const_hints=const_hints,
            extra_init_systems=tuple(extra_systems), **sizes,
        )
    elif isinstance(spec, GenRunSpec):
        report = preflight_gen(spec.genspec,
                               fp_capacity=sizes["fp_capacity"],
                               deep=args.deep)
    else:
        report = preflight_kubeapi(spec.model, deep=args.deep, **sizes)
    print_report(report)
    _journal(args, report)
    return report.exit_code


def _journal(args, report) -> None:
    if not args.journal:
        return
    from ..obs.journal import RunJournal
    from .report import emit_to_journal

    with RunJournal(args.journal, resume=True) as j:
        emit_to_journal(j, report)


if __name__ == "__main__":
    sys.exit(main())
