"""Render + journal preflight analysis results.

One renderer for every consumer: the byte-stable text report (pinned
golden in tier-1), the TLC-style warnings banner the CLI prints, and
the schema-validated `analysis` journal events (obs/schema.py) - so
the report a user reads, the banner the run prints and the events the
dashboard consumes can never disagree.
"""

from __future__ import annotations

from typing import List

from . import AnalysisReport, sorted_findings


def _fmt_set(names) -> str:
    return "{" + ", ".join(sorted(names)) + "}"


def render_spec_section(spec) -> List[str]:
    """The spec-layer section: read/write sets, slot budgets,
    invariant reads, independence pairs - stable order, stable text."""
    lines = [
        f"spec: {spec.root}  variables={_fmt_set(spec.variables)}  "
        f"codec_fields={spec.n_fields}",
        f"actions ({len(spec.actions)}):",
    ]
    for name in sorted(spec.actions):
        a = spec.actions[name]
        extra = ""
        if a.slot_binders:
            extra += "  slots=" + ",".join(
                f"{nm}:{u}/cap4" for nm, u in a.slot_binders
            )
        if a.seq_reads:
            extra += (f"  seq_reads={a.seq_reads}"
                      f" (gated {a.gated_seq_reads})")
        if a.n_disabled == a.n_branches and a.n_branches:
            extra += "  STATICALLY DISABLED"
        lines.append(
            f"  {name}: reads={_fmt_set(a.reads)} "
            f"writes={_fmt_set(a.writes)}"
            f" branches={a.n_branches}{extra}"
        )
    lines.append(f"invariants ({len(spec.invariant_reads)}):")
    for name in sorted(spec.invariant_reads):
        reads = spec.invariant_reads[name]
        tag = "" if reads else "  VACUOUS"
        lines.append(f"  {name}: reads={_fmt_set(reads)}{tag}")
    pairs = spec.independent_pairs
    lines.append(f"independent action pairs ({len(pairs)}):")
    for a, b in pairs:
        lines.append(f"  {a} || {b}")
    return lines


def render_report(report: AnalysisReport) -> str:
    """The full preflight report, byte-stable (golden-pinned)."""
    lines = [f"preflight analysis: {report.name}"]
    if report.spec is not None:
        lines.extend(render_spec_section(report.spec))
    if report.bound_lines:
        lines.extend(report.bound_lines)
    if report.engine_lines:
        lines.append("engine layer:")
        lines.extend(f"  {ln}" for ln in report.engine_lines)
    fs = sorted_findings(report.findings)
    if not fs:
        lines.append("findings: none")
    else:
        lines.append(f"findings ({len(fs)}):")
        for f in fs:
            lines.append(
                f"  [{f.severity}] {f.layer}/{f.check} {f.subject}: "
                f"{f.detail}"
            )
    return "\n".join(lines) + "\n"


def render_banner(log, report: AnalysisReport) -> None:
    """TLC-style warning banner: one line per finding, silent when the
    preflight is clean (pinned CLI transcripts stay byte-identical)."""
    fs = sorted_findings(report.findings)
    if not fs:
        return
    n_err = len(report.errors)
    sev_word = "error(s)" if n_err else "warning(s)"
    n = n_err or len(fs)
    log.msg(1000, f"Preflight analysis: {n} {sev_word} "
                  f"({len(fs)} finding(s) total).", severity=1)
    for f in fs:
        log.msg(
            1000,
            f"Preflight {f.severity} [{f.layer}/{f.check}] "
            f"{f.subject}: {f.detail}",
            severity=1,
        )


def emit_to_journal(journal, report: AnalysisReport,
                    on_event=None) -> None:
    """Stamp one schema-validated `analysis` event per finding plus the
    `analysis_summary` line.  `on_event(kind, info)`-style hooks (the
    supervisor convention) work too, via `on_event`."""

    def _emit(kind: str, **info):
        if journal is not None:
            journal.event(kind, **info)
        if on_event is not None:
            on_event(kind, info)

    for f in sorted_findings(report.findings):
        _emit("analysis", **f.as_event())
    _emit(
        "analysis_summary",
        name=report.name,
        findings=len(report.findings),
        errors=len(report.errors),
        warnings=len(report.warnings),
        wall_s=round(report.wall_s, 6),
    )


def print_report(report: AnalysisReport,
                 out=None) -> None:
    import sys

    (out or sys.stdout).write(render_report(report))
