"""Certified whole-spec abstract interpretation over the struct IR.

The shape-inference pass (struct.shapes) answers "what layout can hold
every reachable value" by ASCENDING iteration with threshold widening
and TypeOK-hint clamping - over-approximate by design, because the
codec only needs an upper bound.  COSTMODEL.json says commit is
sort-dominated and sort cost scales with the packed word count the
codec emits, so those over-approximations are paid for on every chunk
of every run.  This module is the DESCENDING half of the classic
abstract-interpretation recipe (widen up, narrow down, verify):

* **Interval domain** for integer leaves, **length domain** for
  sequences (the SSeq cap), **cardinality domain** for mask-layout
  sets - all expressed as the same Shape lattice the codec consumes,
  so a narrowed bound IS a narrowed layout.
* **Guard refinement**: within one action branch, prime-free guard
  conjuncts (`x < N`, `x = v`, `x \\in S`, `Len(s) = k`) refine the
  pre-state environment before the primed writes are interpreted -
  the precision the ascending pass deliberately skips (it never needs
  it; we do, because `x' = x + 1` under `x < N` must not re-widen).
* **Narrowing fixpoint**: from the widened baseline B0, iterate
  R <- meet(InitShapes ∪ step#(R), R) until stable.
* **Certification**: the result is accepted only when it is verified
  to be a post-fixpoint - `Init ⊑ R` and `step#(R) ⊑ R` under
  shape_leq - so every consumer (codec narrowing, trap elision, the
  runtime certificate column) stands on a machine-checked bound, not
  on the narrowing loop having been bug-free.

Consumers: struct.backend builds the narrowed codec + the on-device
certificate check from a certified report; struct.compile elides
range traps and shrinks slot-lane fans the bounds prove safe; the
preflight report renders the per-variable bound lines.  Pure host
Python over parsed ASTs - no jax, milliseconds per spec.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Dict, List, Optional, Tuple

from ..struct.shapes import (
    SAtoms,
    SBool,
    SInt,
    SRec,
    SSeq,
    SSet,
    SUnion,
    Shape,
    ShapeError,
    ShapeInference,
    _clamp,
    infer_shapes,
    shape_leq,
    shape_of_value,
    typeok_hints,
    universe,
)
from . import SEV_INFO, SEV_WARNING, Finding

MAX_NARROW_ITERS = 64
# ascending-from-bottom budget: guard-refined exact iteration converges
# for guarded counters within their range size; anything slower falls
# back to the descending-narrowing result (never diverges)
MAX_ASCEND_ITERS = 48


# ---------------------------------------------------------------------------
# The abstract transformer: one step# pass with guard refinement
# ---------------------------------------------------------------------------


class _Stepper(ShapeInference):
    """step#: abstract post-state shapes of one Next application from a
    FIXED pre-state environment.  Unlike the ascending parent, writes
    accumulate into `self.writes` (never back into the read
    environment), and prime-free guard conjuncts of a branch refine
    the environment its writes are interpreted under."""

    def __init__(self, ev, variables, init_ast, next_ast, env,
                 const_hints=None):
        super().__init__(ev, variables, init_ast, next_ast)
        self.var_shapes = dict(env)  # read side (pre-state + primes)
        self.writes: Dict[str, Optional[Shape]] = {}
        # field-level guard constraints active for the EXCEPT being
        # abstracted (the `term[n] < MaxTerm` -> `[term EXCEPT ![n] =
        # @ + 1]` pattern: the guard constrains exactly the field the
        # dynamic EXCEPT rewrites, so `@` may be met with it)
        self._cur_fieldguard = None
        if const_hints:
            self.const_hints = dict(const_hints)

    def _record_write(self, name, sh):
        from ..struct.shapes import join

        self.writes[name] = join(self.writes.get(name), sh)
        # primed reads after the assignment see the written shape
        self.var_shapes[name] = join(self.var_shapes.get(name), sh)

    # -- guard refinement --------------------------------------------------

    def _refine_env(self, items, env) -> dict:
        """Refine `env` with every prime-free guard conjunct in `items`
        (refinement is order-free: guards constrain the SAME pre-state
        regardless of where PlusCal emitted them in the conjunction)."""
        out = dict(env)
        for g in items:
            if not isinstance(g, tuple) or not g:
                continue
            if g[0] == "and":
                out = self._refine_env(list(g[1]), out)
                continue
            if g[0] != "cmp":
                continue
            self._refine_cmp(g, out)
        return out

    def _refine_cmp(self, g, env) -> None:
        _, sym, la, ra = g
        if la[0] == "prime" or ra[0] == "prime":
            return
        # normalize: variable (or Len(var) / var[dyn] / Len(var[dyn]))
        # on the left
        for lhs, rhs, s in ((la, ra, sym), (ra, la, _flip(sym))):
            if lhs[0] == "name" and lhs[1] in env:
                self._refine_var(lhs[1], s, rhs, env)
            elif (lhs[0] == "call" and lhs[1] == "Len"
                  and len(lhs[2]) == 1 and lhs[2][0][0] == "name"
                  and lhs[2][0][1] in env):
                self._refine_len(lhs[2][0][1], s, rhs, env)
            else:
                self._refine_field(lhs, s, rhs, env)

    def _refine_field(self, lhs, sym, rhs, env) -> None:
        """Record a field-level guard: `v[i] cmp rhs` or
        `Len(v[i]) cmp rhs` with a DYNAMIC index constrains exactly the
        field a dynamic EXCEPT on `v` rewrites (`@`)."""
        kind = "int"
        if lhs[0] == "call" and lhs[1] == "Len" and len(lhs[2]) == 1:
            kind = "len"
            lhs = lhs[2][0]
        if lhs[0] != "apply" or lhs[1][0] != "name" \
                or lhs[1][1] not in self.variables:
            return
        idx = lhs[2]
        if not (isinstance(idx, tuple) and idx[0] == "name"):
            return  # only binder-indexed reads are matchable
        sh = self._rhs_shape(rhs, env)
        if not isinstance(sh, SInt):
            return
        # keyed by (variable, binder): the guard refines ONLY an EXCEPT
        # whose dynamic index is the same binder occurrence
        key = ("#fieldguard", lhs[1][1])
        env[key] = env.get(key, ()) + ((idx[1], kind, sym, sh),)

    @staticmethod
    def _apply_fieldguard(sh, guards):
        """Meet a field shape with its collected guards (used for `@`
        in a dynamic EXCEPT; the retained, unrewritten fields keep
        their unrefined shapes)."""
        for kind, sym, g in guards or ():
            if kind == "int" and isinstance(sh, SInt):
                lo, hi = sh.lo, sh.hi
                if sym == "<":
                    hi = min(hi, g.hi - 1)
                elif sym == "<=":
                    hi = min(hi, g.hi)
                elif sym == ">":
                    lo = max(lo, g.lo + 1)
                elif sym == ">=":
                    lo = max(lo, g.lo)
                elif sym == "=":
                    lo, hi = max(lo, g.lo), min(hi, g.hi)
                else:
                    continue
                if lo <= hi:
                    sh = SInt(lo, hi)
            elif kind == "len" and isinstance(sh, SSeq):
                cap = sh.cap
                if sym == "<":
                    cap = min(cap, g.hi - 1)
                elif sym in ("<=", "="):
                    cap = min(cap, g.hi)
                else:
                    continue
                if cap >= 0:
                    sh = SSeq(sh.elem, cap)
        return sh

    def _call_shape(self, ast, env):
        """Sharpen Len/Cardinality over the parent's blanket 0..64:
        a bounded sequence's length is 0..cap, a mask set's size is
        0..|element universe| - the bounds guard refinement feeds on."""
        name = ast[1]
        if name == "Len" and len(ast[2]) == 1:
            sh = self._rhs_shape(ast[2][0], env)
            caps = [a.cap for a in
                    (sh.alts if isinstance(sh, SUnion) else (sh,))
                    if isinstance(a, SSeq)]
            if caps and not isinstance(sh, SUnion):
                return SInt(0, max(caps))
        if name == "Cardinality" and len(ast[2]) == 1:
            sh = self._rhs_shape(ast[2][0], env)
            elem = self._elem_shape(sh)
            if isinstance(sh, SSet):
                try:
                    return SInt(0, len(universe(elem, 256)))
                except ShapeError:
                    pass
        return super()._call_shape(ast, env)

    # the dynamic-EXCEPT hook: _abstract("except") on a guarded
    # variable stashes its field guards; _except_one's dynamic-index
    # case then meets `@` with them before abstracting the new value
    def _abstract(self, ast, env):
        if isinstance(ast, tuple) and ast and ast[0] == "except" \
                and isinstance(ast[1], tuple) and ast[1][0] == "name":
            fg = env.get(("#fieldguard", ast[1][1]))
            if fg:
                saved = self._cur_fieldguard
                self._cur_fieldguard = fg
                try:
                    return super()._abstract(ast, env)
                finally:
                    self._cur_fieldguard = saved
        return super()._abstract(ast, env)

    def _except_one(self, sh, path_asts, val_ast, env):
        fg = self._cur_fieldguard
        if fg and isinstance(path_asts[0], tuple) \
                and path_asts[0][0] == "name":
            # only guards on the SAME binder occurrence apply
            fg = tuple(
                (k, s, g) for b, k, s, g in fg
                if b == path_asts[0][1]
            )
        else:
            fg = ()
        if fg and isinstance(sh, SRec) \
                and path_asts[0][0] != "str":
            saved = self._cur_fieldguard
            self._cur_fieldguard = None  # first dynamic level only
            try:
                fields = []
                for fn, s, o in sh.fields:
                    at = self._apply_fieldguard(s, fg)
                    if len(path_asts) > 1:
                        new = self._except_one(at, path_asts[1:],
                                               val_ast, env)
                    else:
                        env2 = dict(env)
                        env2["@"] = at
                        new = self._abstract(val_ast, env2)
                    from ..struct.shapes import join

                    fields.append((fn, join(s, new), o))
                return SRec(tuple(fields))
            finally:
                self._cur_fieldguard = saved
        return super()._except_one(sh, path_asts, val_ast, env)

    def _rhs_shape(self, rhs, env):
        try:
            return self._abstract(rhs, env)
        except (ShapeError, KeyError, TypeError, ValueError,
                RecursionError):
            return None

    def _refine_var(self, name, sym, rhs, env) -> None:
        cur = env.get(name)
        sh = self._rhs_shape(rhs, env)
        if sym == r"\in":
            elem = self._elem_shape(sh)
            if elem is not None:
                env[name] = _meet(cur, elem)
            return
        if sym == "=":
            if sh is not None:
                env[name] = _meet(cur, sh)
            return
        if not isinstance(cur, SInt) or not isinstance(sh, SInt):
            return
        lo, hi = cur.lo, cur.hi
        if sym == "<":
            hi = min(hi, sh.hi - 1)
        elif sym == "<=":
            hi = min(hi, sh.hi)
        elif sym == ">":
            lo = max(lo, sh.lo + 1)
        elif sym == ">=":
            lo = max(lo, sh.lo)
        else:
            return
        if lo <= hi:
            env[name] = SInt(lo, hi)

    def _refine_len(self, name, sym, rhs, env) -> None:
        cur = env.get(name)
        if not isinstance(cur, SSeq):
            return
        sh = self._rhs_shape(rhs, env)
        if not isinstance(sh, SInt):
            return
        cap = cur.cap
        if sym == "<":
            cap = min(cap, sh.hi - 1)
        elif sym == "<=":
            cap = min(cap, sh.hi)
        elif sym == "=":
            cap = min(cap, sh.hi)
        else:
            return
        if cap >= 0:
            env[name] = SSeq(cur.elem, cap)

    @staticmethod
    def _drop_rebound_guards(env, names) -> None:
        """A nested binder that REBINDS a guarded index name invalidates
        the field guards keyed on it (the two occurrences no longer
        denote the same value)."""
        rebound = set(names)
        for key in [k for k in env
                    if isinstance(k, tuple) and k[0] == "#fieldguard"]:
            kept = tuple(g for g in env[key] if g[0] not in rebound)
            if kept:
                env[key] = kept
            else:
                del env[key]

    # -- the walk (guard-refining variant of the parent's) -----------------

    def run_step(self) -> Dict[str, Optional[Shape]]:
        env = dict(self.var_shapes)
        self._walk_refined(self.next_ast, env)
        return self.writes

    def _walk_refined(self, ast, env):
        op = ast[0]
        if op == "and":
            items = list(ast[1])
            env2 = self._refine_env(items, env)
            # sync refined pre-state into prime reads too
            for x in items:
                self._walk_refined(x, env2)
            return
        if op == "or":
            for x in ast[1]:
                self._walk_refined(x, dict(env))
            return
        if op == "exists":
            _, names, dom_ast, body = ast
            dom_sh = self._rhs_shape(dom_ast, env)
            elem = self._elem_shape(dom_sh)
            env2 = dict(env)
            for nm in names:
                env2[nm] = elem
            self._drop_rebound_guards(env2, names)
            return self._walk_refined(body, env2)
        if op == "if":
            self._walk_refined(ast[2], dict(env))
            self._walk_refined(ast[3], dict(env))
            return
        if op == "let":
            from ..struct.parser import Definition

            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    env2[name] = self._rhs_shape(body, env2)
            self._drop_rebound_guards(env2, [n for n, _, _ in ast[1]])
            self._walk_refined(ast[2], env2)
            return
        if op in ("call", "name"):
            from ..struct.parser import Definition
            from ..struct.shapes import _mentions_prime_static

            d = env.get(ast[1])
            if not isinstance(d, Definition):
                d = self.ev.defs.get(ast[1])
            if isinstance(d, Definition) and _mentions_prime_static(
                d.body, self.ev.defs
            ):
                args = ast[2] if op == "call" else []
                env2 = dict(env)
                for p, a in zip(d.params, args):
                    env2[p] = self._rhs_shape(a, env)
                self._drop_rebound_guards(env2, d.params)
                self._walk_refined(d.body, env2)
            return
        if op == "cmp" and ast[1] in ("=", r"\in") \
                and ast[2][0] == "prime":
            name = ast[2][1]
            saved = self.var_shapes
            self.var_shapes = env  # _abstract's prime/name reads
            try:
                rhs = self._rhs_shape(ast[3], env)
                if ast[1] == r"\in":
                    rhs = self._elem_shape(rhs)
            finally:
                self.var_shapes = saved
            from ..struct.shapes import join

            self.writes[name] = join(self.writes.get(name), rhs)
            env[name] = join(env.get(name), rhs)  # later primed reads
            return
        # guards handled by _refine_env; everything else is inert


def _flip(sym: str) -> str:
    return {"<": ">", ">": "<", "<=": ">=", ">=": "<="}.get(sym, sym)


def _meet(a: Optional[Shape], b: Optional[Shape]) -> Optional[Shape]:
    """Best-effort meet via the TypeOK clamp (exact for intervals,
    conservative - returns `a` - where the lattice meet is not
    implemented).  `None` (bottom) absorbs."""
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, SAtoms) and isinstance(b, SAtoms):
        inter = a.atoms & b.atoms
        return SAtoms(inter) if inter else a
    return _clamp(a, b)


# ---------------------------------------------------------------------------
# Cardinality domain (mask-layout set variables)
# ---------------------------------------------------------------------------


def _card_of(ast, cards: Dict[str, int], ev, env_binders, default: int,
             _depth: int = 0) -> int:
    """Upper bound on |ast| given per-variable cardinality bounds.
    `default` (the element-universe size) is the sound fallback for
    anything unmodeled."""
    if _depth > 24 or not isinstance(ast, tuple):
        return default
    op = ast[0]
    if op == "name":
        nm = ast[1]
        if nm in cards:
            return cards[nm]
        if nm in env_binders:
            return default
        if nm in ev.constants and isinstance(ev.constants[nm],
                                             frozenset):
            return min(len(ev.constants[nm]), default)
        d = ev.defs.get(nm)
        if d is not None and not d.params:
            return _card_of(d.body, cards, ev, env_binders, default,
                            _depth + 1)
        return default
    if op == "setlit":
        return min(len(ast[1]), default)
    if op == "binop":
        sym = ast[1]
        ca = _card_of(ast[2], cards, ev, env_binders, default,
                      _depth + 1)
        cb = _card_of(ast[3], cards, ev, env_binders, default,
                      _depth + 1)
        if sym == r"\cup":
            return min(ca + cb, default)
        if sym == r"\cap":
            return min(ca, cb)
        if sym == "\\":
            return ca
        return default
    if op == "setfilter":
        return _card_of(ast[2], cards, ev, env_binders, default,
                        _depth + 1)
    if op == "setmap":
        return _card_of(ast[3], cards, ev, env_binders, default,
                        _depth + 1)
    if op == "if":
        return max(
            _card_of(ast[2], cards, ev, env_binders, default,
                     _depth + 1),
            _card_of(ast[3], cards, ev, env_binders, default,
                     _depth + 1),
        )
    return default


def _card_writes(ast, cards, ev, out: Dict[str, int], binders,
                 set_vars, defaults) -> None:
    """Collect v' = rhs cardinality bounds across all branches."""
    if not isinstance(ast, tuple) or not ast:
        return
    op = ast[0]
    if op in ("and", "or"):
        for x in ast[1]:
            _card_writes(x, cards, ev, out, binders, set_vars, defaults)
        return
    if op == "exists":
        _card_writes(ast[3], cards, ev, out, binders | set(ast[1]),
                     set_vars, defaults)
        return
    if op == "if":
        _card_writes(ast[2], cards, ev, out, binders, set_vars, defaults)
        _card_writes(ast[3], cards, ev, out, binders, set_vars, defaults)
        return
    if op == "let":
        _card_writes(ast[2], cards, ev, out, binders, set_vars, defaults)
        return
    if op in ("call", "name"):
        from ..struct.parser import Definition
        from ..struct.shapes import _mentions_prime_static

        d = ev.defs.get(ast[1])
        if isinstance(d, Definition) and _mentions_prime_static(
            d.body, ev.defs
        ):
            _card_writes(d.body, cards, ev, out,
                         binders | set(d.params), set_vars, defaults)
        return
    if op == "cmp" and ast[1] == "=" and ast[2][0] == "prime" \
            and ast[2][1] in set_vars:
        name = ast[2][1]
        c = _card_of(ast[3], cards, ev, binders, defaults[name])
        out[name] = max(out.get(name, 0), c)
        return
    if op == "cmp" and ast[1] == r"\in" and ast[2][0] == "prime" \
            and ast[2][1] in set_vars:
        # v' \in S picks an ELEMENT of S; its cardinality is unmodeled
        name = ast[2][1]
        out[name] = defaults[name]


# ---------------------------------------------------------------------------
# The report
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class BoundReport:
    """The certified result of the whole-spec abstract interpretation:
    per-variable narrowed shapes (the codec consumes these verbatim),
    per-set-variable cardinality bounds (slot-lane budgets), and the
    machine-checked certification verdict."""

    root: str
    variables: Tuple[str, ...]
    baseline: Dict[str, Shape]  # the widened ascending fixpoint
    bounds: Dict[str, Shape]  # the certified narrowed shapes
    card_bounds: Dict[str, int]  # mask-layout vars: certified max |v|
    card_universe: Dict[str, int]  # same vars: element-universe size
    certified: bool
    iters: int
    wall_s: float
    baseline_nbits: int = 0
    narrowed_nbits: int = 0
    baseline_words: int = 0
    narrowed_words: int = 0

    def digest(self) -> str:
        """Stable identity of the bound environment - the engine-memo /
        checkpoint-meta key component (a narrowed engine is a different
        compile than an un-narrowed one)."""
        h = hashlib.sha256()
        for v in self.variables:
            h.update(f"{v}={self.bounds.get(v)!r};".encode())
        for v in sorted(self.card_bounds):
            h.update(f"|{v}|<={self.card_bounds[v]};".encode())
        h.update(b"certified" if self.certified else b"uncertified")
        return h.hexdigest()[:16]

    def narrowed(self) -> bool:
        return self.certified and (
            self.narrowed_nbits < self.baseline_nbits
            or any(self.card_bounds[v] < self.card_universe[v]
                   for v in self.card_bounds)
        )

    def render_lines(self) -> List[str]:
        """The byte-stable bound-report section (the -analyze view)."""
        lines = [
            "certified reachable bounds"
            + ("" if self.certified else " (NOT certified - narrowing "
               "disabled, baseline layout kept)")
            + f": {self.baseline_nbits} -> {self.narrowed_nbits} bits "
            f"({self.baseline_words} -> {self.narrowed_words} words)"
        ]
        for v in self.variables:
            base, cur = self.baseline.get(v), self.bounds.get(v)
            tag = "" if base == cur else "  NARROWED"
            card = ""
            if v in self.card_bounds:
                card = (f"  |{v}| <= {self.card_bounds[v]}"
                        f"/{self.card_universe[v]}")
            lines.append(f"  {v}: {_shape_str(cur)}{card}{tag}")
        return lines

    def findings(self) -> List[Finding]:
        out = []
        if not self.certified:
            out.append(Finding(
                layer="spec", check="bound-certification",
                severity=SEV_WARNING, subject=self.root,
                detail=("the narrowed bound environment could not be "
                        "verified as a post-fixpoint of the abstract "
                        "transformer; narrowing is disabled and the "
                        "baseline codec layout is kept"),
            ))
        elif self.narrowed_nbits < self.baseline_nbits:
            out.append(Finding(
                layer="spec", check="bound-narrowing",
                severity=SEV_INFO, subject=self.root,
                detail=(f"certified reachable bounds narrow the packed "
                        f"state from {self.baseline_nbits} to "
                        f"{self.narrowed_nbits} bits "
                        f"({self.baseline_words} -> "
                        f"{self.narrowed_words} uint32 words); run "
                        "with -narrow to use the narrowed codec"),
            ))
        return out


def _shape_str(sh: Optional[Shape]) -> str:
    if sh is None:
        return "bottom"
    if isinstance(sh, SInt):
        return f"int {sh.lo}..{sh.hi}"
    if isinstance(sh, SBool):
        return "bool"
    if isinstance(sh, SAtoms):
        return "{" + ", ".join(sorted(sh.atoms)) + "}"
    if isinstance(sh, SSet):
        return f"subset-of[{_shape_str(sh.elem)}]"
    if isinstance(sh, SSeq):
        return f"seq[{_shape_str(sh.elem)}] len<={sh.cap}"
    if isinstance(sh, SRec):
        inner = ", ".join(
            f"{f}{'?' if o else ''}: {_shape_str(s)}"
            for f, s, o in sh.fields
        )
        return "[" + inner + "]"
    if isinstance(sh, SUnion):
        return " | ".join(_shape_str(a) for a in sh.alts)
    return type(sh).__name__


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def _init_shapes(system, const_hints=None,
                 extra_systems=()) -> Dict[str, Optional[Shape]]:
    """Join of shape_of_value over every initial state (of the anchor
    system plus any extra per-configuration systems - the sweep-class
    audit enumerates each config's Init host-side)."""
    from ..struct.shapes import join

    out: Dict[str, Optional[Shape]] = {v: None for v in system.variables}
    for sys_ in (system, *extra_systems):
        for st in sys_.initial_states():
            for v, val in zip(sys_.variables, st):
                out[v] = join(out[v], shape_of_value(val))
    return out


def _step_writes(system, env, const_hints=None) -> Dict[str, Shape]:
    st = _Stepper(system.ev, system.variables, system.init_ast,
                  system.next_ast, env, const_hints=const_hints)
    return st.run_step()


def _certify(system, bounds, init, const_hints=None) -> bool:
    """Machine-check that `bounds` is a post-fixpoint: Init ⊑ bounds
    and step#(bounds) ⊑ bounds."""
    for v in system.variables:
        if not shape_leq(init.get(v), bounds.get(v)):
            return False
    try:
        writes = _step_writes(system, dict(bounds),
                              const_hints=const_hints)
    except (ShapeError, RecursionError):
        return False
    for v, sh in writes.items():
        if not shape_leq(sh, bounds.get(v)):
            return False
    return True


def _mask_universe(sh) -> Optional[int]:
    """Element-universe size of a top-level mask-layout set shape, or
    None when the variable is not mask-layout."""
    if not isinstance(sh, SSet):
        return None
    try:
        return len(universe(sh.elem, 1 << 16))
    except ShapeError:
        return None


def analyze_bounds(model, const_hints: Optional[Dict[str, Shape]] = None,
                   extra_init_systems=()) -> BoundReport:
    """Run the certified abstract interpretation on a loaded
    StructModel.  `const_hints` widens CONSTANT names to abstract
    values (the sweep-class audit); `extra_init_systems` contributes
    additional per-configuration Init sets to the seed."""
    from ..struct.codec import StructCodec

    t0 = time.time()
    system = model.system
    hints = typeok_hints(system.ev, model.invariants, system.variables)
    baseline = infer_shapes(system.ev, system.variables,
                            system.init_ast, system.next_ast,
                            hints=hints, const_hints=const_hints)

    init = _init_shapes(system, const_hints=const_hints,
                        extra_systems=extra_init_systems)

    # descending narrowing from the widened baseline (joined with every
    # configuration's Init seed: the anchor's ascending run only saw its
    # own initial states)
    from ..struct.shapes import join

    baseline = {
        v: join(baseline.get(v), init.get(v))
        for v in system.variables
    }

    iters = 0

    def _iterate(start, combine):
        """Fixpoint loop over F(R) = Init ∪ step#(R), post-processed by
        `combine(candidate, previous)`.  Returns the stable env or None
        when the budget runs out / the transformer fails."""
        nonlocal iters
        cur = dict(start)
        for _ in range(MAX_NARROW_ITERS):
            iters += 1
            try:
                writes = _step_writes(system, dict(cur),
                                      const_hints=const_hints)
            except (ShapeError, RecursionError):
                return None
            nxt = {}
            for v in system.variables:
                cand = join(init.get(v), writes.get(v))
                nxt[v] = combine(cand, cur.get(v))
            if nxt == cur:
                return cur
            cur = nxt
        return None

    # candidate 1: exact ascending iteration from bottom (guard-refined,
    # no widening) - the least-fixpoint chase; converges for guarded
    # counters, diverges (budget exhausted -> skipped) for unguarded
    # growth
    ascend = None
    asc_budget = iters + MAX_ASCEND_ITERS
    cur_a = dict(init)
    while iters < asc_budget:
        iters += 1
        try:
            writes = _step_writes(system, dict(cur_a),
                                  const_hints=const_hints)
        except (ShapeError, RecursionError):
            break
        nxt = {
            v: join(init.get(v), writes.get(v))
            for v in system.variables
        }
        if nxt == cur_a:
            ascend = cur_a
            break
        cur_a = nxt

    # candidate 2: descending narrowing from the widened baseline
    descend = _iterate(baseline, lambda cand, prev: _meet(cand, prev))

    certified = False
    cur = dict(baseline)
    for cand in (ascend, descend, baseline):
        if cand is None:
            continue
        if _certify(system, cand, init, const_hints=const_hints):
            cur = dict(cand)
            certified = True
            break

    # cardinality bounds for mask-layout set variables
    card_bounds: Dict[str, int] = {}
    card_universe: Dict[str, int] = {}
    set_vars = {}
    for v in system.variables:
        u = _mask_universe(cur.get(v))
        if u is not None:
            set_vars[v] = u
    if set_vars and certified:
        cards = {v: 0 for v in set_vars}
        for sys_ in (system, *extra_init_systems):
            for st in sys_.initial_states():
                for v, val in zip(sys_.variables, st):
                    if v in cards and isinstance(val, frozenset):
                        cards[v] = max(cards[v], len(val))
        for _ in range(MAX_NARROW_ITERS):
            writes: Dict[str, int] = {}
            _card_writes(system.next_ast, cards, system.ev, writes,
                         frozenset(), set(set_vars), set_vars)
            nxt = {
                v: min(max(cards[v], writes.get(v, 0)), set_vars[v])
                for v in cards
            }
            if nxt == cards:
                break
            cards = nxt
        # certify: one more transfer application must not grow any bound
        writes = {}
        _card_writes(system.next_ast, cards, system.ev, writes,
                     frozenset(), set(set_vars), set_vars)
        for v in set_vars:
            bound = min(max(cards[v], writes.get(v, 0)), set_vars[v])
            card_bounds[v] = bound if bound == cards[v] else set_vars[v]
            card_universe[v] = set_vars[v]

    rep = BoundReport(
        root=model.root_name,
        variables=system.variables,
        baseline=baseline,
        bounds={v: cur.get(v) for v in system.variables},
        card_bounds=card_bounds,
        card_universe=card_universe,
        certified=certified,
        iters=iters,
        wall_s=time.time() - t0,
    )
    try:
        base_cdc = StructCodec(system.variables, baseline)
        rep.baseline_nbits = base_cdc.nbits
        rep.baseline_words = base_cdc.n_words
        narrow_cdc = StructCodec(system.variables, rep.bounds)
        rep.narrowed_nbits = narrow_cdc.nbits
        rep.narrowed_words = narrow_cdc.n_words
    except (ShapeError, ValueError):
        # a layout the codec cannot build disables narrowing loudly
        rep.certified = False
        rep.bounds = dict(baseline)
        rep.narrowed_nbits = rep.baseline_nbits
        rep.narrowed_words = rep.baseline_words
    return rep
