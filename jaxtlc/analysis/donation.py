"""Make use-after-donate loud on CPU (JAXTLC_DEBUG_DONATION=1).

`make_backend_engine(donate=True)` donates the carry on device
backends; on CPU XLA has no donation, so a driver that wrongly feeds
the same carry twice works on CPU and corrupts on TPU - the exact
hazard class the engine-layer donation audit flags statically
(analysis.engine_audit).  This module is the RUNTIME teeth: with
``JAXTLC_DEBUG_DONATION=1`` (on in the test suite, tests/conftest.py) a
factory that REQUESTED donation wraps its run/step functions so the
input carry's buffers are deleted after each call - reuse then raises
``RuntimeError: Array has been deleted`` immediately, at the reuse
site, on any backend.

Leaves that the jit returns by identity (pass-through outputs share the
input Array object) are skipped, so poisoning never deletes a buffer
the caller legitimately holds through the RESULT.  AOT paths
(`fn.lower(carry).compile()`) bypass the wrapper - they also bypass the
donation request on CPU, so there is nothing to simulate there.
"""

from __future__ import annotations

import os


def debug_donation_enabled() -> bool:
    return os.environ.get("JAXTLC_DEBUG_DONATION", "") not in (
        "", "0", "false", "off"
    )


def _poison(carry, out) -> None:
    import jax

    keep = {id(x) for x in jax.tree_util.tree_leaves(out)}
    for leaf in jax.tree_util.tree_leaves(carry):
        if isinstance(leaf, jax.Array) and id(leaf) not in keep:
            try:
                leaf.delete()
            except Exception:
                pass  # already deleted / committed elsewhere: fine


class PoisoningFn:
    """Callable wrapper simulating donation semantics: after `fn(carry)`
    the input carry is dead.  All other attribute access (``.lower``,
    the donation tags) forwards to the wrapped function."""

    def __init__(self, fn):
        self._inner = fn

    def __call__(self, carry):
        out = self._inner(carry)
        _poison(carry, out)
        return out

    def __getattr__(self, name):
        return getattr(self._inner, name)


def wrap_if_debugging(fn, donate_requested: bool):
    """Apply the poisoning wrapper when the debug mode is on AND the
    factory asked for donation (a donate=False engine must stay safe to
    reuse - the supervisor's retry loop depends on it)."""
    if donate_requested and debug_donation_enabled():
        return PoisoningFn(fn)
    return fn
