"""Engine-free lint gate over a specs tree (CI tooling, ISSUE 10).

Runs the spec-layer lints (speclint) plus the certified abstract
interpretation (absint) over every ``MC.cfg`` under a directory -
milliseconds per spec, no jax, no XLA - and fails (nonzero) on any
error-severity finding.  The committed ``specs/`` tree is gated in
tier-1 (tests/test_absint.py) so a spec edit that introduces an
error-class lint cannot land silently; ``tools/lintgate.py`` and
``python -m jaxtlc.analysis --gate`` run the same pass standalone.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

from . import SEV_ERROR, AnalysisReport, Finding, sorted_findings


def find_configs(root: str) -> List[str]:
    """Every MC.cfg under `root`, sorted for stable output."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            if f == "MC.cfg":
                out.append(os.path.join(dirpath, f))
    return sorted(out)


def gate_one(cfg_path: str) -> Tuple[str, Optional[AnalysisReport], str]:
    """(spec label, report-or-None, skip reason).  Specs the struct
    frontend cannot load are SKIPPED, not failed - the gate audits what
    the struct IR can see; the other frontends have their own tests."""
    from ..struct.loader import StructLoadError, load
    from ..struct.parser import StructParseError
    from ..struct.shapes import ShapeError
    from .absint import analyze_bounds
    from .speclint import analyze_spec

    label = os.path.relpath(cfg_path)
    try:
        model = load(cfg_path)
        spec = analyze_spec(model)
        bounds = analyze_bounds(model)
    except (StructLoadError, StructParseError, ShapeError,
            RecursionError, ValueError, OSError) as e:
        return label, None, f"{type(e).__name__}: {e}"
    rep = AnalysisReport(name=f"struct:{model.root_name}",
                         spec=spec,
                         findings=list(spec.findings))
    rep.bound_lines = bounds.render_lines()
    rep.extend(bounds.findings())
    return label, rep, ""


def run_gate(root: str, out=None, baseline: Optional[set] = None) -> int:
    """Gate every spec under `root`.  Returns the exit code: nonzero
    iff a NEW error-severity finding appeared (a `baseline` set of
    (check, subject) pairs - the committed, known findings - is
    tolerated, so the gate flags regressions, not history)."""
    out = out or sys.stdout
    t0 = time.time()
    baseline = baseline or set()
    configs = find_configs(root)
    if not configs:
        out.write(f"lint gate: no MC.cfg under {root}\n")
        return 2
    new_errors: List[Tuple[str, Finding]] = []
    n_findings = 0
    for cfg in configs:
        label, rep, skip = gate_one(cfg)
        if rep is None:
            out.write(f"gate {label}: SKIPPED ({skip})\n")
            continue
        fs = sorted_findings(rep.findings)
        n_findings += len(fs)
        errs = [f for f in fs if f.severity == SEV_ERROR
                and (f.check, f.subject) not in baseline]
        new_errors.extend((label, f) for f in errs)
        status = "ok" if not fs else (
            f"{len(fs)} finding(s)"
            + (f", {len(errs)} NEW error(s)" if errs else "")
        )
        out.write(f"gate {label}: {status}\n")
        for f in fs:
            out.write(f"  [{f.severity}] {f.layer}/{f.check} "
                      f"{f.subject}: {f.detail}\n")
    out.write(
        f"lint gate: {len(configs)} spec(s), {n_findings} finding(s), "
        f"{len(new_errors)} new error(s), {time.time() - t0:.2f}s\n"
    )
    return 1 if new_errors else 0
