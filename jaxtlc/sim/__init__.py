"""Simulation tier: vmapped random-walk smoke checking (ISSUE 14).

TLC ships a randomized simulation mode next to its exhaustive engine
(the TLA+ Trifecta survey, PAPERS.md) because exhaustive BFS caps the
reachable workload set: configs whose state spaces do not fit a table
still yield real assurance from many deep random walks.  This package
is that mode, TPU-shaped: W walker lanes, each holding ONE packed
state, stepped depth-D through the SAME SpecBackend expand/invariant
kernels every exhaustive engine uses (engine.backend - no second
compiler path), choosing a uniformly random enabled successor per step
with counter-based threefry bits so every lane is a pure function of
``(run_seed, lane_id)``.

That purity is the whole design: a tripped invariant / deadlock /
assertion lane needs NO on-device trace storage - ``sim.replay``
re-walks the lane host-side from its seed, reproduces the identical
trajectory bit-for-bit, and the violation renders as the same
PlusCal-level exit-12 trace a BFS run would print.

Zero cross-lane communication makes the walk embarrassingly
vmappable: ``SimEngine`` batches (seed, constants-config) lanes the
way serve.sweep batches constant configs - swept CONSTANTs ride as
state fields, so seeds x configs check in one device dispatch.

A simulation verdict is a SMOKE verdict: "ok" means no violation was
found in the sampled behaviors, never that none exists.  The artifact
cache (struct.artifacts) is bypassed on this path - an incomplete
search must not publish into the exhaustive verdict tier.
"""

from .engine import (  # noqa: F401
    SimCarry,
    SimEngine,
    SimResult,
    get_sim_engine,
    make_sim_engine,
    result_from_sim_carry,
)
from .replay import replay_lane  # noqa: F401
