"""Liveness on sampled traces: TLC-simulate-style lasso detection.

The exhaustive struct path checks plain ``P ~> Q`` properties with a
greatest-fixpoint over the full reachable graph; a random walk cannot
do that, but it CAN falsify: when a lane's depth-D trajectory revisits
a state, the segment between the two visits is a genuine cycle of the
state graph (every consecutive pair in a walk is a taken transition),
and an admissible cycle containing no Q-state answers an unanswered
P-state with a real infinite counterexample behavior - exactly what
TLC's ``-simulate`` reports.

Admissibility matches the host oracle's WF_vars(Next) semantics
(struct.oracle.check_leads_to): a cycle through more than one state
takes state-changing transitions forever and is always admissible; a
single-state "cycle" (a self-loop lane or a frozen dead lane) is
admissible only if the state has NO state-changing successor - the
honest host check, because forever-stuttering while a state-changing
action is enabled is exactly what weak fairness forbids.

A clean pass proves nothing (the walk is sampled); only lassos can
falsify.  The caller keeps its skip notice for property shapes this
checker cannot express.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np


class WalkLassoResult(NamedTuple):
    """One property's verdict over all walk lanes."""

    name: str
    holds: bool  # no violating lasso found - NOT a liveness proof
    lanes: int
    lassos: int  # lanes whose trajectory closed a cycle
    violation_lane: int  # -1 when holds
    prefix: List[tuple]  # decoded states before the cycle
    cycle: List[tuple]  # decoded cycle states (first repeats)


def walk_trajectories(model, walkers: int, depth: int, seed: int,
                      check_deadlock: bool = True) -> np.ndarray:
    """[D+1, W, F] walk states re-derived from the seed through the
    (memoized, jitted) sim step function - counter-based threefry makes
    every trajectory a pure function of (seed, lane), so this replays
    the exact lanes a prior run of the same geometry walked."""
    from .engine import get_sim_engine

    _b, init_fn, _run_fn, step_fn = get_sim_engine(
        model, walkers, depth, 0, check_deadlock=check_deadlock
    )
    carry = init_fn(seed)
    snaps = [np.asarray(carry.states)]
    for _ in range(depth):
        carry = step_fn(carry)
        snaps.append(np.asarray(carry.states))
    return np.stack(snaps)


def check_walk_leads_to(model, p_ast, q_ast, name: str,
                        trajectories: np.ndarray,
                        system=None) -> WalkLassoResult:
    """Check ``P ~> Q`` against [D+1, W, F] walk trajectories.

    Host-side: predicates evaluate through the same ``ev.eval`` the
    oracle uses, memoized per distinct state (walks revisit heavily);
    lasso detection is a first-occurrence scan per lane."""
    system = system or model.system
    ev = system.ev
    D1, W, F = trajectories.shape

    from ..struct.cache import get_backend

    cdc = get_backend(model, True).cdc
    decoded: dict = {}
    pq: dict = {}

    def state_of(vec) -> tuple:
        key = vec.tobytes()
        if key not in decoded:
            decoded[key] = cdc.decode(vec)
        return decoded[key]

    def eval_pq(st: tuple):
        if st not in pq:
            env = dict(ev.constants)
            env.update(zip(system.variables, st))
            try:
                p = ev.eval(p_ast, env) is True
                q = ev.eval(q_ast, env) is True
            except Exception:
                p, q = False, True  # uninterpretable: never falsify
            pq[st] = (p, q)
        return pq[st]

    def stutter_admissible(st: tuple) -> bool:
        # single-state cycle: admissible under WF_vars(Next) only if
        # the state has no state-changing successor (terminated, or a
        # Terminating-style self-loop-only state)
        try:
            return all(nxt == st for _lbl, nxt in
                       system.successors(st))
        except Exception:
            return False

    lassos = 0
    for lane in range(W):
        trace = [state_of(trajectories[t, lane]) for t in range(D1)]
        first: dict = {}
        k = t = -1
        for i, st in enumerate(trace):
            if st in first:
                k, t = first[st], i
                break
            first[st] = i
        if t < 0:
            continue  # no cycle closed within depth: proves nothing
        cycle = trace[k:t]
        lassos += 1
        if len(set(cycle)) == 1 and not stutter_admissible(cycle[0]):
            continue
        if any(eval_pq(st)[1] for st in cycle):
            continue  # the cycle answers every pending P with a Q
        for i in range(t):
            p, _q = eval_pq(trace[i])
            if p and not any(eval_pq(trace[j])[1]
                             for j in range(i, t)):
                return WalkLassoResult(
                    name=name, holds=False, lanes=W, lassos=lassos,
                    violation_lane=lane, prefix=trace[:k],
                    cycle=cycle,
                )
    return WalkLassoResult(name=name, holds=True, lanes=W,
                           lassos=lassos, violation_lane=-1,
                           prefix=[], cycle=[])


def expressible(ast) -> Optional[str]:
    """None when the walk checker can handle this property AST, else
    the skip reason (the same plain ``P ~> Q`` subset the exhaustive
    struct path checks)."""
    if ast[0] != "leadsto" or ast[1][0] == "box":
        return ("only plain P ~> Q is checked on sampled behaviors")
    return None
