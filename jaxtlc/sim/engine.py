"""Device-resident random-walk engine (the simulation tier's core).

One ``lax.while_loop`` drives W walker lanes depth-D: every body vmaps
the backend's successor kernel over the lanes' CURRENT states, checks
the invariants on each lane's CHOSEN next state, and advances every
lane by one uniformly random enabled successor.  The choice bits are
counter-based threefry (``jax.random.fold_in``): transition ``d`` of
lane ``l`` under run seed ``s`` consumes exactly
``bits(fold_in(fold_in(PRNGKey(s), l), d))`` (``d = 0`` picks the
lane's initial state), so a lane's whole trajectory is a pure function
of ``(s, l)`` - the property ``sim.replay`` turns into exact host-side
violation replay with zero on-device trace storage.

The walk reuses the exhaustive engines' seam wholesale: any
``engine.backend.SpecBackend`` (struct-compiled, generic, the
hand-tuned KubeAPI kernel) plugs in unchanged - there is no second
compiler path.  The optional distinct-fingerprint estimate reuses the
existing device fpset as a SAMPLING FILTER: chosen states' fingerprints
insert each step, and the running distinct count is a lower-bound
estimate that saturates honestly (``fp_saturated``) instead of halting
the walk when the table fills.

Violation semantics (first wins, deterministic): invariant codes in
backend order > PlusCal assert > deadlock > codec slot overflow, ties
broken by lowest lane index - so the reported ``(lane, step)`` is a
pure function of the seed too, and replay cannot disagree with the
device about WHICH violation fired.
"""

from __future__ import annotations

import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..engine.backend import SpecBackend
from ..engine.bfs import (
    DEFAULT_FP_HIGHWATER,
    OK,
    VIOL_ASSERT,
    VIOL_DEADLOCK,
    VIOL_SLOT_OVERFLOW,
    VIOLATION_NAMES,
)
from ..engine.fingerprint import (
    DEFAULT_FP_INDEX,
    DEFAULT_SEED,
    fp64_words_mxu,
)
from ..engine.fpset import fpset_insert_sorted, fpset_new

DEFAULT_WALKERS = 256
DEFAULT_DEPTH = 100
# seed-batch width of a warm SimEngine (the smoke job class's vmapped
# dispatch, mirroring serve.sweep.DEFAULT_WIDTH)
DEFAULT_SIM_WIDTH = 4


class SimCarry(NamedTuple):
    """The whole walk state: W lanes' current states + cursors +
    counters.  Every leaf is fixed-shape, so the carry checkpoints
    through engine.checkpoint's generic pytree snapshots and vmaps
    over a seed batch axis unchanged."""

    key: jnp.ndarray  # uint32[2] threefry key of THIS run's seed
    states: jnp.ndarray  # [W, F] int32 current state per lane
    step_i: jnp.ndarray  # int32 transitions completed (the cursor)
    alive: jnp.ndarray  # [W] bool: lane still walking
    steps_taken: jnp.ndarray  # [W] int32 transitions this lane took
    generated: jnp.ndarray  # uint32 enabled successors examined
    transitions: jnp.ndarray  # uint32 transitions taken (all lanes)
    act_taken: jnp.ndarray  # [n_labels] uint32 actions taken
    viol: jnp.ndarray  # int32 first-wins violation code
    viol_lane: jnp.ndarray  # int32 lane that tripped it
    viol_step: jnp.ndarray  # int32 transition index (0 = an Init state)
    viol_state: jnp.ndarray  # [F] int32 the violating state
    viol_action: jnp.ndarray  # int32 action taken into it (-1 = none)
    # --- optional distinct-fp sampling filter (None = estimate off) ---
    fps: tuple = None  # engine.fpset.FPSet
    distinct: jnp.ndarray = None  # uint32 distinct fps sampled
    fp_sat: jnp.ndarray = None  # bool: filter full, estimate is a floor


class SimResult(NamedTuple):
    """Host-side result of one walk run.  Field names deliberately
    mirror engine.bfs.CheckResult where the fact is the same fact
    (violation / action_generated / wall_s), so the serve plane's
    result plumbing serves both engines; `distinct` is the SAMPLED
    estimate (0 when the filter is off) and `depth` the deepest step
    any lane took - neither claims exhaustiveness."""

    walkers: int
    depth: int  # requested walk depth
    seed: int
    steps: int  # transition rounds completed
    generated: int  # enabled successors examined
    transitions: int  # transitions actually taken
    distinct: int  # sampled distinct-state estimate (0 = filter off)
    fp_saturated: bool
    violation: int
    violation_name: str
    violation_state: np.ndarray
    violation_action: int
    violation_lane: int
    violation_step: int
    action_generated: dict  # {label: times taken} - walk composition
    action_distinct: dict  # always {} (walks do not dedup per action)
    depth_hist: tuple  # sorted (steps_taken, n_lanes) pairs
    halted: int  # lanes that stopped early (deadlock w/ -nodeadlock)
    wall_s: float
    queue_left: int = 0  # CheckResult-compat (walks carry no frontier)


def make_sim_engine(
    backend: SpecBackend,
    walkers: int = DEFAULT_WALKERS,
    depth: int = DEFAULT_DEPTH,
    fp_capacity: int = 0,
    fp_index: int = DEFAULT_FP_INDEX,
    fp_seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    check_deadlock: bool = None,
):
    """Build ``(init_fn, run_fn, step_fn)`` for the random-walk engine.

    ``init_fn(seed, inits=None) -> SimCarry`` seeds every lane with a
    random member of the Init set (`inits` overrides it - the sweep
    path seeds per-config Inits exactly like serve.sweep).  The seed is
    DATA, not geometry: one compile serves every seed, and a stacked
    batch of carries with different seeds vmaps through ``run_fn`` in
    one dispatch.

    ``run_fn(carry)`` walks to depth / first violation / all-lanes-
    halted; ``step_fn(carry)`` advances ONE transition round (the
    supervised driver's segment unit - the (seed, step) cursor lives in
    the carry, so checkpoints are ordinary pytree snapshots).

    ``fp_capacity > 0`` carries the distinct-fp sampling filter: the
    existing device fpset, fed each round with the lanes' chosen
    states.  Pure telemetry - it feeds no control flow, and it
    SATURATES (sticky ``fp_sat``) instead of halting the walk.
    """
    cdc = backend.cdc
    F = cdc.n_fields
    L = backend.n_lanes
    W = int(walkers)
    n_labels = len(backend.labels)
    inv_check = backend.inv_check
    inv_codes = backend.inv_codes
    nbits = cdc.nbits
    label_ids = jnp.arange(n_labels, dtype=jnp.int32)
    lane_ids = jnp.arange(W, dtype=jnp.uint32)
    if check_deadlock is None:
        check_deadlock = backend.check_deadlock
    sample = fp_capacity > 0
    step = backend.step

    def lane_bits(key, step_i):
        """[W] uint32 choice bits for transition round `step_i`: the
        counter-based draw replay re-derives per lane host-side."""
        def one(lane):
            k = jax.random.fold_in(jax.random.fold_in(key, lane),
                                   step_i)
            return jax.random.bits(k, dtype=jnp.uint32)

        return jax.vmap(one)(lane_ids)

    def sample_insert(fps, distinct, sat, states, mask):
        """Feed the sampling filter; saturate instead of halting."""
        packed = cdc.pack(states)
        lo, hi = fp64_words_mxu(packed, nbits, fp_index, fp_seed)
        would_over = (distinct.astype(jnp.int32) + W) > int(
            fp_capacity * fp_highwater
        )
        sat = sat | would_over
        fps, is_new, _, _ = fpset_insert_sorted(
            fps, lo, hi, mask & ~sat
        )
        distinct = distinct + is_new.sum().astype(jnp.uint32)
        return fps, distinct, sat

    def init_fn(seed, inits=None) -> SimCarry:
        if inits is None:
            inits = backend.initial_vectors()
        inits = jnp.asarray(inits)
        n0 = inits.shape[0]
        key = jax.random.PRNGKey(seed)
        # round 0: each lane draws its initial state
        idx = (lane_bits(key, 0) % jnp.uint32(n0)).astype(jnp.int32)
        states = inits[idx]
        # invariants hold on the chosen Init states too (TLC checks
        # them before the first Next application)
        inv0 = jax.vmap(inv_check)(states)
        viol = jnp.int32(OK)
        viol_lane = jnp.int32(-1)
        viol_state = jnp.zeros(F, jnp.int32)
        for k, code in enumerate(inv_codes):
            bad = (inv0 & (1 << k)) == 0
            hit = bad.any() & (viol == OK)
            lane = jnp.argmax(bad).astype(jnp.int32)
            viol = jnp.where(hit, code, viol)
            viol_lane = jnp.where(hit, lane, viol_lane)
            viol_state = jnp.where(hit, states[lane], viol_state)
        extra = {}
        if sample:
            fps, distinct, sat = sample_insert(
                fpset_new(fp_capacity), jnp.uint32(0), jnp.bool_(False),
                states, jnp.ones(W, bool),
            )
            extra = dict(fps=fps, distinct=distinct, fp_sat=sat)
        return SimCarry(
            key=key,
            states=states,
            step_i=jnp.int32(0),
            alive=jnp.ones(W, bool),
            steps_taken=jnp.zeros(W, jnp.int32),
            generated=jnp.uint32(W),
            transitions=jnp.uint32(0),
            act_taken=jnp.zeros(n_labels, jnp.uint32),
            viol=viol,
            viol_lane=viol_lane,
            viol_step=jnp.int32(0),
            viol_state=viol_state,
            viol_action=jnp.int32(-1),
            **extra,
        )

    def body(c: SimCarry) -> SimCarry:
        succs, valid, action, afail, ovf = jax.vmap(step)(c.states)
        valid = valid & c.alive[:, None]
        n_enabled = valid.sum(axis=1).astype(jnp.uint32)
        dead = c.alive & (n_enabled == 0)

        # the uniform draw: idx-th ENABLED lane in lane order (modulo
        # bias at 2^32 is negligible and, crucially, deterministic -
        # the replay derives the identical index from the same bits)
        d = c.step_i + 1
        bits = lane_bits(c.key, d)
        idx = (bits % jnp.maximum(n_enabled, 1)).astype(jnp.int32)
        csum = jnp.cumsum(valid.astype(jnp.int32), axis=1)
        chosen = jnp.argmax(
            (csum == (idx + 1)[:, None]) & valid, axis=1
        ).astype(jnp.int32)
        take = c.alive & (n_enabled > 0)
        rows = jnp.arange(W)
        picked = succs[rows, chosen]
        new_states = jnp.where(take[:, None], picked, c.states)
        acts = action[rows, chosen].astype(jnp.int32)
        ch_afail = take & afail[rows, chosen]
        ch_ovf = take & ovf[rows, chosen]

        # invariants on the chosen next states only: the walk checks
        # the states it VISITS, exactly TLC simulation's discipline
        inv = jax.vmap(inv_check)(new_states)
        inv_bad = [
            take & ((inv & (1 << k)) == 0)
            for k in range(len(inv_codes))
        ]

        # first-wins violation, ties to the lowest lane: priority
        # mirrors the exhaustive expand stage (invariant > assert >
        # deadlock > slot) so the two tiers never name the same bug
        # differently
        viol = c.viol
        viol_lane = c.viol_lane
        viol_step = c.viol_step
        viol_state = c.viol_state
        viol_action = c.viol_action
        dead_mask = dead if check_deadlock else jnp.zeros(W, bool)
        for code, vmask, states_src, has_act in (
            *((code, bad, new_states, True)
              for code, bad in zip(inv_codes, inv_bad)),
            (VIOL_ASSERT, ch_afail, c.states, True),
            (VIOL_DEADLOCK, dead_mask, c.states, False),
            (VIOL_SLOT_OVERFLOW, ch_ovf, c.states, True),
        ):
            hit = vmask.any() & (viol == OK)
            lane = jnp.argmax(vmask).astype(jnp.int32)
            viol = jnp.where(hit, code, viol)
            viol_lane = jnp.where(hit, lane, viol_lane)
            viol_step = jnp.where(hit, d, viol_step)
            viol_state = jnp.where(hit, states_src[lane], viol_state)
            viol_action = jnp.where(
                hit, acts[lane] if has_act else jnp.int32(-1),
                viol_action,
            )

        act_taken = c.act_taken + (
            (acts[:, None] == label_ids[None, :]) & take[:, None]
        ).sum(axis=0).astype(jnp.uint32)
        extra = {}
        if sample:
            fps, distinct, sat = sample_insert(
                c.fps, c.distinct, c.fp_sat, new_states, take
            )
            extra = dict(fps=fps, distinct=distinct, fp_sat=sat)
        return c._replace(
            states=new_states,
            step_i=d,
            alive=c.alive & ~dead,
            steps_taken=jnp.where(take, d, c.steps_taken),
            generated=c.generated + valid.sum().astype(jnp.uint32),
            transitions=c.transitions + take.sum().astype(jnp.uint32),
            act_taken=act_taken,
            viol=viol,
            viol_lane=viol_lane,
            viol_step=viol_step,
            viol_state=viol_state,
            viol_action=viol_action,
            **extra,
        )

    def cond(c: SimCarry):
        return (
            (c.step_i < depth) & (c.viol == OK) & c.alive.any()
        )

    # donate=False throughout: carries are re-seeded per run (cheap at
    # walk sizes), the supervised driver snapshots the last-good carry
    # while the next segment runs, and SimEngine's sequential parity
    # baseline feeds the same carry value twice
    run_fn = jax.jit(lambda c: lax.while_loop(cond, body, c))
    step_fn = jax.jit(lambda c: lax.cond(cond(c), body, lambda x: x, c))
    for fn in (run_fn, step_fn):
        # donation metadata for the engine audit (analysis.engine_audit)
        fn.donate_requested = False
        fn.donates_carry = False
    return init_fn, run_fn, step_fn


def sim_done(carry: SimCarry, depth: int) -> bool:
    """Host-side termination check (the supervised driver's fence)."""
    if int(carry.viol) != OK:
        return True
    return int(carry.step_i) >= depth or not bool(
        np.asarray(carry.alive).any()
    )


def depth_histogram(steps_taken) -> tuple:
    """Sorted (steps, lanes) pairs of the walks' final depths."""
    vals, counts = np.unique(np.asarray(steps_taken), return_counts=True)
    return tuple((int(v), int(n)) for v, n in zip(vals, counts))


def result_from_sim_carry(
    carry: SimCarry, wall_s: float, backend: SpecBackend,
    walkers: int, depth: int, seed: int, viol_names: dict = None,
) -> SimResult:
    labels = backend.labels
    act = np.asarray(carry.act_taken)
    viol = int(carry.viol)
    vname = (viol_names or backend.viol_names or {}).get(viol) or \
        VIOLATION_NAMES.get(viol, f"violation {viol}")
    steps_taken = np.asarray(carry.steps_taken)
    return SimResult(
        walkers=int(walkers),
        depth=int(depth),
        seed=int(seed),
        steps=int(carry.step_i),
        generated=int(carry.generated),
        transitions=int(carry.transitions),
        distinct=int(carry.distinct) if carry.distinct is not None else 0,
        fp_saturated=(bool(carry.fp_sat)
                      if carry.fp_sat is not None else False),
        violation=viol,
        violation_name=vname,
        violation_state=np.asarray(carry.viol_state),
        violation_action=int(carry.viol_action),
        violation_lane=int(carry.viol_lane),
        violation_step=int(carry.viol_step),
        action_generated={
            labels[i]: int(v) for i, v in enumerate(act) if v
        },
        action_distinct={},
        depth_hist=depth_histogram(steps_taken),
        halted=int((~np.asarray(carry.alive)).sum()),
        wall_s=wall_s,
    )


# ---------------------------------------------------------------------------
# struct-model memo (the api / pool / test share one compiled walk)
# ---------------------------------------------------------------------------


def sim_engine_key(model, walkers: int, depth: int, fp_capacity: int,
                   check_deadlock: bool = True) -> tuple:
    """The sim-engine memo/pool key: spec meaning x walk geometry.
    The SEED is deliberately absent - it is run data, so one warm
    engine serves every seed (the smoke job class's whole economics)."""
    from ..struct.cache import model_key

    return ("sim", model_key(model), int(walkers), int(depth),
            int(fp_capacity), bool(check_deadlock))


_SIM_MEMO = None  # built lazily (struct.cache._LRUMemo, cap 8)


def get_sim_engine(model, walkers: int, depth: int,
                   fp_capacity: int = 0, check_deadlock: bool = True):
    """Memoized (backend, init_fn, run_fn, step_fn) for a struct model
    (the struct.cache discipline: repeated sim runs of one model in a
    process never re-trace; jax's jit cache keeps the compiled walk
    alive because the memo returns the SAME closures)."""
    from ..struct.cache import _LRUMemo, get_backend

    global _SIM_MEMO
    if _SIM_MEMO is None:
        _SIM_MEMO = _LRUMemo(8)
    key = sim_engine_key(model, walkers, depth, fp_capacity,
                         check_deadlock)
    hit = _SIM_MEMO.get(key)
    if hit is None:
        backend = get_backend(model, check_deadlock)
        hit = (backend,) + make_sim_engine(
            backend, walkers=walkers, depth=depth,
            fp_capacity=fp_capacity, check_deadlock=check_deadlock,
        )
        _SIM_MEMO.put(key, hit)
    return hit


class SimEngine:
    """A warm smoke-class engine: one compiled walk + one batched AOT
    executable that runs up to `width` (seed, config) lanes per device
    dispatch - serve.sweep.SweepEngine's shape applied to the seed
    axis.  `params` (swept constant domains) additionally promotes the
    swept CONSTANTs to state fields through the SAME sweep compiler, so
    seeds x configs batch in one dispatch with one compile per class."""

    def __init__(
        self,
        model,
        params: Optional[Dict[str, Tuple[int, int]]] = None,
        walkers: int = DEFAULT_WALKERS,
        depth: int = DEFAULT_DEPTH,
        fp_capacity: int = 0,
        check_deadlock: bool = True,
        width: int = DEFAULT_SIM_WIDTH,
    ):
        from ..struct.cache import enable_persistent_cache

        enable_persistent_cache()
        self.model = model
        self.params = (
            {c: (int(lo), int(hi)) for c, (lo, hi) in params.items()}
            if params else None
        )
        self.walkers = int(walkers)
        self.depth = int(depth)
        self.width = max(1, int(width))
        self.fp_capacity = int(fp_capacity)
        if self.params:
            from ..serve.sweep import sweep_backend

            self.backend = sweep_backend(model, self.params,
                                         check_deadlock)
            init_fn, run_fn, step_fn = make_sim_engine(
                self.backend, walkers=self.walkers, depth=self.depth,
                fp_capacity=self.fp_capacity,
                check_deadlock=check_deadlock,
            )
        else:
            self.backend, init_fn, run_fn, step_fn = get_sim_engine(
                model, self.walkers, self.depth,
                fp_capacity=self.fp_capacity,
                check_deadlock=check_deadlock,
            )
        self._init_jit = jax.jit(init_fn)
        self._run_fn = run_fn
        self._vrun = jax.jit(jax.vmap(run_fn))
        self._aot = None
        self._aot_seq = None

    # -- carries -----------------------------------------------------------

    def carry_for(self, seed: int,
                  values: Optional[Dict[str, int]] = None) -> SimCarry:
        """A fresh walk carry for one (seed, constants-config) lane."""
        if self.params:
            from ..serve.sweep import config_inits

            inits = config_inits(self.model, self.params,
                                 values or {}, self.backend.cdc)
            return self._init_jit(seed, jnp.asarray(inits))
        return self._init_jit(seed)

    def _stack(self, items: List[tuple]):
        if not items:
            raise ValueError("empty sim batch")
        if len(items) > self.width:
            raise ValueError(
                f"{len(items)} sim lanes > width {self.width} "
                "(the scheduler slices batches to width)"
            )
        pad = items + [items[-1]] * (self.width - len(items))
        carries = [self.carry_for(s, v) for s, v in pad]
        return jax.tree.map(lambda *xs: jnp.stack(xs), *carries)

    def _result(self, carry, wall_s: float, seed: int) -> SimResult:
        return result_from_sim_carry(
            carry, wall_s, self.backend, self.walkers, self.depth,
            seed,
        )

    # -- execution ---------------------------------------------------------

    def run(self, items: List[tuple]) -> List[SimResult]:
        """Walk up to `width` (seed, config-values-or-None) lanes in
        ONE device dispatch; per-lane results in submission order."""
        stacked = self._stack(items)
        if self._aot is None:
            self._aot = self._vrun.lower(stacked).compile()
        t0 = time.time()
        out = jax.block_until_ready(self._aot(stacked))
        wall = time.time() - t0
        return [
            self._result(jax.tree.map(lambda x: x[k], out), wall,
                         items[k][0])
            for k in range(len(items))
        ]

    def run_sequential(self, items: List[tuple]) -> List[SimResult]:
        """The parity baseline: the SAME compiled walk, one lane at a
        time (tests pin run() bit-for-bit against this, fpset sampling
        table included)."""
        results = []
        for seed, values in items:
            carry = self.carry_for(seed, values)
            if self._aot_seq is None:
                self._aot_seq = self._run_fn.lower(carry).compile()
            t0 = time.time()
            out = jax.block_until_ready(self._aot_seq(carry))
            results.append(self._result(out, time.time() - t0, seed))
        return results
