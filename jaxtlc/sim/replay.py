"""Seed-exact host replay of one walker lane (the violation story).

A sim lane's trajectory is a pure function of ``(run_seed, lane_id)``
(sim.engine): transition ``d`` consumes exactly
``bits(fold_in(fold_in(PRNGKey(seed), lane), d))`` and picks the
idx-th ENABLED successor lane in kernel-lane order.  This module
re-derives the identical draw host-side and re-steps the lane through
the SAME backend kernel, eagerly, one state at a time - so the replay
reproduces the device trajectory bit-for-bit (tests pin this) with no
on-device trace storage, and the walk prefix IS the counterexample
trace: decoded through the struct codec and rendered as TLA conjuncts,
it is the PlusCal-level exit-12 trace a BFS run would print for the
same forced path.

Eager execution is deliberate: a replay is <= depth single-state
kernel steps - milliseconds of work that must never cost an XLA
compile (tier-1's zero-extra-compile discipline).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.backend import SpecBackend
from ..engine.bfs import (
    OK,
    VIOL_ASSERT,
    VIOL_DEADLOCK,
    VIOL_SLOT_OVERFLOW,
)


class ReplayedWalk(NamedTuple):
    """One lane's re-walked trajectory, host-side."""

    seed: int
    lane: int
    # the visited states as raw [F] int32 field vectors, init first
    fields: List[np.ndarray]
    # action label per entry (None for the initial state)
    labels: List[Optional[str]]
    violation: int  # OK when the walk just ran out of steps
    violation_step: int  # index into `fields` of the violating state
    halted: bool  # lane stopped at a successor-less state (no-deadlock)


def _draw(key, lane: int, step: int) -> int:
    """The counter-based choice bits of (lane, step) - scalar twin of
    the engine's vmapped lane_bits (threefry is shape-independent, so
    the two agree bit-for-bit; tests pin it)."""
    k = jax.random.fold_in(jax.random.fold_in(key, lane), step)
    return int(jax.random.bits(k, dtype=jnp.uint32))


def replay_lane(
    backend: SpecBackend,
    seed: int,
    lane: int,
    steps: int,
    inits: Optional[np.ndarray] = None,
    check_deadlock: bool = None,
) -> ReplayedWalk:
    """Re-walk lane `lane` of run `seed` for up to `steps` transitions.

    Stops early at the first violation on the walked path (invariant >
    assert > deadlock > slot overflow - the engine's own priority, so
    the replay lands on the same state the device reported)."""
    if check_deadlock is None:
        check_deadlock = backend.check_deadlock
    key = jax.random.PRNGKey(seed)
    if inits is None:
        inits = backend.initial_vectors()
    inits = np.asarray(inits)
    n0 = inits.shape[0]
    labels = backend.labels
    inv_codes = backend.inv_codes

    state = inits[_draw(key, lane, 0) % n0]
    fields = [np.asarray(state, np.int32)]
    lbls: List[Optional[str]] = [None]

    def inv_viol(vec) -> int:
        bits = int(backend.inv_check(jnp.asarray(vec)))
        for k, code in enumerate(inv_codes):
            if not (bits >> k) & 1:
                return code
        return OK

    code = inv_viol(state)
    if code != OK:
        return ReplayedWalk(seed, lane, fields, lbls, code, 0, False)

    for d in range(1, steps + 1):
        succs, valid, action, afail, ovf = backend.step(
            jnp.asarray(state)
        )
        valid = np.asarray(valid)
        n = int(valid.sum())
        if n == 0:
            if check_deadlock:
                return ReplayedWalk(seed, lane, fields, lbls,
                                    VIOL_DEADLOCK, len(fields) - 1,
                                    False)
            return ReplayedWalk(seed, lane, fields, lbls, OK,
                                len(fields) - 1, True)
        idx = _draw(key, lane, d) % n
        chosen = int(np.flatnonzero(valid)[idx])
        state = np.asarray(succs)[chosen].astype(np.int32)
        act_id = int(np.asarray(action).reshape(-1)[chosen])
        fields.append(state)
        lbls.append(labels[act_id] if 0 <= act_id < len(labels)
                    else None)
        if bool(np.asarray(ovf).reshape(-1)[chosen]):
            return ReplayedWalk(seed, lane, fields, lbls,
                                VIOL_SLOT_OVERFLOW, len(fields) - 1,
                                False)
        if bool(np.asarray(afail).reshape(-1)[chosen]):
            return ReplayedWalk(seed, lane, fields, lbls, VIOL_ASSERT,
                                len(fields) - 1, False)
        code = inv_viol(state)
        if code != OK:
            return ReplayedWalk(seed, lane, fields, lbls, code,
                                len(fields) - 1, False)
    return ReplayedWalk(seed, lane, fields, lbls, OK, len(fields) - 1,
                        False)


def walk_trace(walk: ReplayedWalk, cdc) -> List[Tuple[tuple, object]]:
    """The walk as [(decoded state tuple, action label | None), ...] -
    the exact shape struct.oracle.violation_trace returns, so the
    api's trace renderer prints a replayed walk and a BFS-found trace
    through one code path (byte-for-byte transcripts)."""
    return [
        (cdc.decode(vec), lbl)
        for vec, lbl in zip(walk.fields, walk.labels)
    ]
