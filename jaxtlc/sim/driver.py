"""Supervised simulation runs: the (seed, step) cursor as a run unit.

The walk carry is a fixed-shape pytree whose ``step_i`` IS the resume
cursor (every lane is a pure function of ``(seed, lane)``, so a carry
at step k plus the run seed determines the remainder of the run
exactly).  This driver applies the resil conventions to it: segments
of ``ckpt_every`` transition rounds, a SIGTERM/SIGINT drain that
writes a final generation and reports ``interrupted`` (the CLI's
exit-75 / -recover contract), CRC-manifested generation-numbered
checkpoints through engine.checkpoint's generic pytree snapshots, and
deterministic fault injection (resil.faults ``sigterm@K`` fires at
segment K) so the recovery path is proven in tier-1, not believed.

There is no degradation ladder here on purpose: the walk allocates
nothing that grows (the optional fp sampling filter SATURATES instead
of halting), so the only recoveries a smoke run needs are preemption
and resume.
"""

from __future__ import annotations

import json
import time
from typing import NamedTuple, Optional

import jax
from jax import lax

from ..engine.checkpoint import (
    load_latest_generation,
    save_generation,
)
from ..resil.faults import FaultInjector, FaultPlan
from ..resil.supervisor import _SignalCatcher
from .engine import (
    SimResult,
    get_sim_engine,
    result_from_sim_carry,
    sim_done,
    sim_engine_key,
)

SIM_FORMAT = 1

_SEG_MEMO = None  # compiled segment executables (struct.cache._LRUMemo)


def _compiled_segment(model, walkers, depth, fp_capacity,
                      check_deadlock, ckpt_every, step_fn, template):
    """AOT segment executable, memoized on (engine key, cadence): the
    template's shapes are seed-independent, so one compile serves every
    run of a model - an api -simulate resubmit performs zero fresh XLA
    compiles (the pool discipline applied to the supervised path)."""
    from ..struct.cache import _LRUMemo

    global _SEG_MEMO
    if _SEG_MEMO is None:
        _SEG_MEMO = _LRUMemo(8)
    key = sim_engine_key(model, walkers, depth, fp_capacity,
                         check_deadlock) + (int(ckpt_every),)
    hit = _SEG_MEMO.get(key)
    if hit is None:
        @jax.jit
        def segment(c):
            return lax.fori_loop(0, ckpt_every,
                                 lambda _, cc: step_fn(cc), c)

        hit = segment.lower(template).compile()
        _SEG_MEMO.put(key, hit)
    return hit


class SimSupervised(NamedTuple):
    result: SimResult
    interrupted: bool
    segments: int
    ckpt_writes: int


def sim_meta(model, seed: int, walkers: int, depth: int,
             fp_capacity: int, check_deadlock: bool) -> dict:
    """The checkpoint meta stanza: spec meaning + the FULL walk
    identity, seed included - a -recover against a different seed (or
    walk geometry) is a different trajectory and must mismatch loudly,
    never silently splice two runs."""
    from ..struct.backend import struct_meta_config

    return json.loads(json.dumps({
        "format": SIM_FORMAT,
        "kind": "sim",
        "config": struct_meta_config(model),
        "seed": int(seed),
        "walkers": int(walkers),
        "depth": int(depth),
        "fp_capacity": int(fp_capacity),
        "check_deadlock": bool(check_deadlock),
    }))


def _emit(on_event, kind: str, **info):
    if on_event is not None:
        on_event(kind, info)


def _progress_info(carry, walkers: int, depth: int, seed: int) -> dict:
    return dict(
        phase="progress", walkers=int(walkers), depth=int(depth),
        steps=int(carry.step_i), transitions=int(carry.transitions),
        seed=int(seed),
        distinct_est=(int(carry.distinct)
                      if carry.distinct is not None else 0),
    )


def run_sim(model, seed: int = 0, walkers: int = 256, depth: int = 100,
            fp_capacity: int = 0, check_deadlock: bool = True
            ) -> SimResult:
    """One unsupervised walk run: AOT-compile the fused while_loop,
    dispatch once, time execution only (the bfs.check discipline)."""
    backend, init_fn, run_fn, _ = get_sim_engine(
        model, walkers, depth, fp_capacity=fp_capacity,
        check_deadlock=check_deadlock,
    )
    carry = jax.jit(init_fn)(seed)
    compiled = run_fn.lower(carry).compile()
    t0 = time.time()
    out = jax.block_until_ready(compiled(carry))
    wall = time.time() - t0
    return result_from_sim_carry(out, wall, backend, walkers, depth,
                                 seed)


def run_sim_supervised(
    model,
    seed: int = 0,
    walkers: int = 256,
    depth: int = 100,
    fp_capacity: int = 0,
    check_deadlock: bool = True,
    ckpt_path: Optional[str] = None,
    ckpt_every: int = 64,
    resume: bool = False,
    faults: Optional[FaultPlan] = None,
    on_event=None,
    drain=None,
) -> SimSupervised:
    """Segmented walk run with preemption safety and cursor resume.

    `on_event(kind, info)` receives schema-shaped journal events:
    ``sim`` progress rows at every segment fence, ``checkpoint`` /
    ``recovery`` / ``interrupted`` with the resil meanings.  A resumed
    run continues from the checkpointed (seed, step) cursor and its
    final carry is bit-for-bit the uninterrupted run's
    (tests/test_sim.py pins this through a sigterm@K fault)."""
    backend, init_fn, _, step_fn = get_sim_engine(
        model, walkers, depth, fp_capacity=fp_capacity,
        check_deadlock=check_deadlock,
    )
    meta = sim_meta(model, seed, walkers, depth, fp_capacity,
                    check_deadlock)
    template = jax.jit(init_fn)(seed)
    compiled = _compiled_segment(
        model, walkers, depth, fp_capacity, check_deadlock,
        ckpt_every, step_fn, template,
    )
    carry = template
    if resume:
        if not ckpt_path:
            raise FileNotFoundError("-recover needs a sim -checkpoint")
        path, saved_meta, carry = load_latest_generation(
            ckpt_path, template
        )
        for key, want in meta.items():
            got = saved_meta.get(key)
            if got != want:
                raise ValueError(
                    f"sim checkpoint {key} mismatch: {got!r} != "
                    f"{want!r} (a walk is a pure function of its seed "
                    "and geometry - resumes cannot cross them)"
                )
        _emit(on_event, "recovery", path=path,
              depth=int(carry.step_i), generated=int(carry.generated),
              distinct=(int(carry.distinct)
                        if carry.distinct is not None else 0),
              queue=0)

    injector = FaultInjector(faults)
    t0 = time.time()
    segments = 0
    ckpt_writes = 0
    interrupted = False
    # the programmatic drain twin of _SignalCatcher (ISSUE 17): the
    # serve scheduler preempts ONE sim job without signaling the server
    drained = (lambda: drain is not None and drain.is_set())
    with _SignalCatcher() as sig:
        while not sim_done(carry, depth):
            injector.segment_start(segments)
            if sig.hit is not None or drained():
                interrupted = True
                break
            carry = jax.block_until_ready(compiled(carry))
            segments += 1
            _emit(on_event, "sim",
                  **_progress_info(carry, walkers, depth, seed))
            if ckpt_path and not sim_done(carry, depth):
                tck = time.time()
                path = save_generation(ckpt_path, carry, meta)
                ckpt_writes += 1
                _emit(on_event, "checkpoint", path=path,
                      seconds=round(time.time() - tck, 6), label="sim")
            if sig.hit is not None or drained():
                interrupted = True
                break
        if (sig.hit is not None or drained()) \
                and not sim_done(carry, depth):
            interrupted = True
    wall = time.time() - t0
    if interrupted:
        path = None
        if ckpt_path:
            path = save_generation(ckpt_path, carry, meta)
            ckpt_writes += 1
        _emit(on_event, "interrupted", signum=int(sig.hit or 0),
              path=path, generated=int(carry.generated),
              distinct=(int(carry.distinct)
                        if carry.distinct is not None else 0),
              queue=0, wall_s=round(wall, 6))
    result = result_from_sim_carry(carry, wall, backend, walkers,
                                   depth, seed)
    return SimSupervised(result=result, interrupted=interrupted,
                         segments=segments, ckpt_writes=ckpt_writes)
