"""Command-line interface - the TLC invocation contract (E14).

Replaces `java tlc2.TLC -config MC.cfg ...` for the KubeAPI spec family:

    python -m jaxtlc.cli check /path/to/Model_1/MC.cfg \\
        [-workers tpu] [-fpset JaxFPSet] [-fp 51] [-sharded N] \\
        [-chunk 1024] [-nodeadlock] [-noTool]

Reads the unmodified reference artifacts (MC.cfg + sibling MC.tla + the
toolbox .launch if present - BASELINE.json's `-fpset JaxFPSet -workers tpu`
contract), runs the exhaustive check on the fused device engine (or the
sharded multi-device engine with -sharded), and emits the TLC structured
log protocol.  On violation it re-runs in host mode to reconstruct the
counterexample trace and prints it TLC-style with PlusCal action labels.

Exit codes: 0 = no error; 12 = safety violation (TLC's EC.ExitStatus
convention for violations); 13 = liveness violation; 75 = interrupted
(SIGTERM/SIGINT) OR capacity-exhausted (the degradation ladder's final
rung) with a final checkpoint written - resume with -recover;
1 = usage/config error (including non-regrowable codec slot overflow).

Robustness (the resil supervisor wraps the KubeAPI-path engines):
capacity exhaustion walks a degradation ladder instead of aborting -
-auto-grow (default) doubles a saturated fpset/queue/route resource
after a probe allocation confirms it fits; when the probe is denied,
-spill (default auto) activates the host-RAM fingerprint spill tier so
the run completes inside the device memory it has; then chunk shrink;
then checkpoint + exit 75.  -retry N retries segments around transient
device errors (RESOURCE_EXHAUSTED is classified as deterministic and
goes to the ladder, never the retry budget); -checkpoint writes
CRC-verified generation-numbered snapshots (spilling runs pair each
with a host-tier .spill sibling) and -recover loads the newest intact
one (auto-grown geometry and the host tier travel with the checkpoint).
"""

from __future__ import annotations

import argparse
import os
import sys

# The check orchestration lives in jaxtlc.api now (the engine-as-a-
# library refactor, ISSUE 9): this module is the argparse shim.  The
# names below are re-exported for callers that grew up against the old
# CLI-owns-everything layout (tests, tools).
from .api import (  # noqa: F401 - compatibility re-exports
    CheckRequest,
    CheckOutcome,
    run_check,
    _dispatch_check,
    _finish_journal,
    _open_journal,
    _preflight_gate,
    _resume_command,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="jaxtlc")
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("check", help="exhaustively check a TLC model config")
    c.add_argument("config", help="path to MC.cfg (sibling MC.tla is read)")
    c.add_argument("-workers", default="tpu", help="TLC contract knob")
    c.add_argument("-frontend", default="auto",
                   choices=["auto", "hand", "gen", "struct"],
                   help="spec frontend: auto picks hand-tuned KubeAPI / "
                        "gen-subset / structural as applicable; struct "
                        "forces the full-module structural path (runs "
                        "ANY spec, KubeAPI included)")
    c.add_argument("-fpset", default="JaxFPSet",
                   choices=["JaxFPSet", "DiskFPSet"],
                   help="JaxFPSet = device-resident fingerprint table; "
                        "DiskFPSet = native host tier (disk-bounded, the "
                        "OffHeapDiskFPSet analog)")
    c.add_argument("-fp", type=int, default=None, help="fp polynomial index")
    c.add_argument("-sharded", type=int, default=0, metavar="N",
                   help="run the sharded engine over N devices")
    c.add_argument("-chunk", type=int, default=1024)
    c.add_argument("-pipeline", dest="pipeline", action="store_true",
                   default=False,
                   help="software-pipeline the device engines: commit "
                        "(dedup/enqueue) of block k-1 overlaps expansion "
                        "of block k, with the sharded verdict-return "
                        "all_to_all deferred behind the next routing "
                        "collective.  Bit-for-bit identical counts; for "
                        "maximum overlap run with HALF the unpipelined "
                        "sweet-spot -chunk (PERF.md round 7).  A "
                        "checkpoint records this setting: -recover "
                        "must use the same mode")
    c.add_argument("-no-pipeline", dest="pipeline", action="store_false",
                   help="(default) the fused single-stage step bodies")
    c.add_argument("-sort-free", dest="sortfree", action="store_const",
                   const=True, default=None,
                   help="commit through the hash-slab dedup instead of "
                        "the two full-width stable sorts (ISSUE 12): "
                        "scatter-max in-batch dedup + a probe-width "
                        "claimant compaction, inherited by every engine "
                        "at the expand/commit seam (fused, -pipeline, "
                        "-sharded, spill, -phase-timing, -narrow, "
                        "-coverage).  Results are bit-for-bit the "
                        "sorted path's - full signature AND fpset "
                        "table words (bench.py --commit-ab gates it).  "
                        "Default auto: on at -chunk >= 2048, where the "
                        "fitted cost model shows the sorts at 89%% of "
                        "commit (COSTMODEL.json); off below, where "
                        "they are cheap.  A checkpoint records the "
                        "resolved mode: -recover must match")
    c.add_argument("-no-sort-free", dest="sortfree", action="store_const",
                   const=False,
                   help="force the sorted dedup commit at any chunk")
    c.add_argument("-deferred-inv", dest="deferredinv",
                   action="store_const", const=True, default=None,
                   help="distinct-first expand (ISSUE 15): evaluate "
                        "invariants and the certified-bound check at "
                        "the commit stage, on the fresh-insert "
                        "claimants only, instead of on every chunk*L "
                        "candidate lane - TLC checks a state when it "
                        "is first generated, and first generation IS "
                        "the distinct fpset insert.  Inherited by "
                        "every engine at the expand/commit seam "
                        "(fused, -pipeline, -sharded owner-side, "
                        "spill, -phase-timing, -narrow, -coverage); "
                        "-simulate ignores it (every walker state is "
                        "fresh - the sim tier keeps its immediate "
                        "per-walker invariant path).  Verdict, "
                        "counters, fpset table words and rendered "
                        "traces are bit-for-bit the immediate "
                        "path's (bench.py --expand-ab gates it); the "
                        "reported violating LANE follows the pinned "
                        "highest-lane rule (the PR 12 dedup rep "
                        "convention) instead of first-lane.  Default "
                        "auto: on at -chunk >= 2048, where the "
                        "fitted cost model shows the invariant "
                        "sweep dominating the step (COSTMODEL.json); "
                        "off below.  A checkpoint records the "
                        "resolved mode: -recover must match")
    c.add_argument("-no-deferred-inv", dest="deferredinv",
                   action="store_const", const=False,
                   help="force immediate per-candidate invariant/cert "
                        "evaluation at any chunk")
    c.add_argument("-symmetry", dest="symmetry", action="store_const",
                   const=True, default=None,
                   help="device-resident symmetry reduction (ISSUE "
                        "18): statically verify which CONSTANT sets "
                        "the spec treats as fully permutation-"
                        "symmetric (the TLC SYMMETRY condition, "
                        "checked against the spec text - no "
                        "annotation needed), then canonicalize every "
                        "successor to its orbit representative on "
                        "device before fingerprinting, so the fpset "
                        "dedups orbits.  Same verdict, same rendered "
                        "trace, legitimately fewer DISTINCT/"
                        "GENERATED states (up to the product of "
                        "|S|! over the reduced sets).  A runtime "
                        "orbit certificate re-checks canonicalization "
                        "on every iteration (single device): a trip "
                        "is a loud error verdict, never a silently "
                        "wrong count.  Struct frontend only; "
                        "inherited by every engine at the expand/"
                        "commit seam.  Default off (counts shrink - "
                        "this is not a transparent perf mode).  A "
                        "checkpoint records the mode: -recover must "
                        "match")
    c.add_argument("-no-symmetry", dest="symmetry", action="store_const",
                   const=False,
                   help="force the unreduced full state space")
    c.add_argument("-por", dest="por", action="store_const",
                   const=True, default=None,
                   help="partial-order pruning (ISSUE 18): where a "
                        "provably safe action is enabled (independent "
                        "of every other action, invisible to every "
                        "invariant, and a monotone counter - so no "
                        "all-ample cycle can starve the rest), expand "
                        "only that action's transitions instead of "
                        "every commutative interleaving.  Same "
                        "verdict, legitimately fewer states; the "
                        "journal `reduce` event reports transitions "
                        "pruned.  Struct frontend only; default off.  "
                        "A checkpoint records the mode: -recover "
                        "must match")
    c.add_argument("-no-por", dest="por", action="store_const",
                   const=False,
                   help="force full interleaving expansion")
    c.add_argument("-routefactor", type=float, default=2.0,
                   help="sharded all_to_all bucket size as a multiple of "
                        "the mean per-owner candidate count (raise after "
                        "a routing-bucket-overflow halt)")
    c.add_argument("-qcap", type=int, default=1 << 15)
    c.add_argument("-fpcap", type=int, default=1 << 20)
    c.add_argument("-checkpoint", default="", metavar="PATH",
                   help="periodic engine snapshots to PATH (TLC checkpoint "
                        "analog); resume with -recover")
    c.add_argument("-checkpointevery", type=int, default=256, metavar="N",
                   help="chunks between checkpoints")
    c.add_argument("-recover", action="store_true",
                   help="resume from -checkpoint PATH (TLC -recover "
                        "analog); the newest intact generation is loaded, "
                        "with fallback past a torn newest file")
    c.add_argument("-auto-grow", dest="autogrow", action="store_true",
                   default=True,
                   help="(default) on fpset/queue/route saturation, double "
                        "the saturated resource, migrate the carry, and "
                        "resume instead of aborting")
    c.add_argument("-no-auto-grow", dest="autogrow", action="store_false",
                   help="disable auto-regrow: capacity exhaustion aborts "
                        "with the sizing hint (the pre-supervisor "
                        "behavior); without -checkpoint this also "
                        "restores the raw fused single-dispatch engine")
    c.add_argument("-spill", dest="spill", action="store_const",
                   const="on", default="auto",
                   help="prefer the host-RAM fingerprint spill tier at "
                        "the FIRST fpset saturation (skip the regrow "
                        "attempt).  Default auto: regrow first, spill "
                        "when the doubled table's probe allocation is "
                        "denied (RESOURCE_EXHAUSTED) or -max-regrow is "
                        "reached.  Cold fingerprints migrate to a host "
                        "store behind an on-device membership filter; "
                        "results stay bit-for-bit exact, at a host "
                        "sync per chunk (PERF.md round 10)")
    c.add_argument("-no-spill", dest="spill", action="store_const",
                   const="off",
                   help="remove the spill rung from the degradation "
                        "ladder: a denied fpset regrow then falls "
                        "through to chunk shrink / checkpoint + exit 75")
    c.add_argument("-max-regrow", dest="maxregrow", type=int, default=8,
                   metavar="N",
                   help="max auto-regrow events per run (each doubles one "
                        "resource, so 8 allows 256x growth)")
    c.add_argument("-retry", type=int, default=2, metavar="N",
                   help="retries per segment around transient device/XLA "
                        "errors (exponential backoff with jitter, "
                        "restoring the last good state)")
    c.add_argument("-faults", default="", metavar="PLAN",
                   help="self-test: deterministic fault plan for the "
                        "supervisor (e.g. 'transient@1,sigterm@3,"
                        "write_fail@2,truncate@1'; tools/chaos.py drives "
                        "this end-to-end)")
    c.add_argument("-compile-cache", dest="compilecache", default="",
                   metavar="DIR",
                   help="persistent XLA compile-cache directory for "
                        "compiled steps (default ~/.cache/jaxtlc/xla, or "
                        "$JAXTLC_COMPILE_CACHE; warm-starts repeated runs "
                        "of the same model - delete the directory to "
                        "clear it)")
    c.add_argument("-no-compile-cache", dest="nocompilecache",
                   action="store_true",
                   help="disable the persistent compile cache for this "
                        "run")
    c.add_argument("-artifact-cache", dest="artifactcache", default="",
                   metavar="DIR",
                   help="incremental re-checking artifact store "
                        "(struct frontend): cached VERDICTS keyed on "
                        "the spec's semantic digest (an unchanged spec "
                        "returns its verdict without building an "
                        "engine) and cached REACHABLE SETS keyed on "
                        "the behavior digest (an invariant-only edit "
                        "skips BFS and re-evaluates just the "
                        "invariants).  Default ~/.cache/jaxtlc/"
                        "artifacts, or $JAXTLC_ARTIFACT_CACHE (=off "
                        "disables); artifacts are CRC-verified and "
                        "written only on clean verdicts - "
                        "tools/cachectl.py lists/verifies/GCs them")
    c.add_argument("-no-artifact-cache", dest="noartifactcache",
                   action="store_true",
                   help="disable the artifact cache (both tiers) for "
                        "this run")
    c.add_argument("-recheck", action="store_true",
                   help="force a full re-check: bypass the artifact "
                        "cache on read (the run still refreshes the "
                        "artifacts it produces)")
    c.add_argument("-obs", dest="obs", action="store_true", default=True,
                   help="(default) carry the on-device observability "
                        "counter ring: one per-level telemetry row "
                        "(generated/distinct/queue/occupancy/per-action "
                        "counts), read back at segment fences and "
                        "journaled as `level` events.  Pure telemetry: "
                        "results are bit-for-bit identical to -no-obs "
                        "(bench.py --obs-ab gates overhead at <= 2%)")
    c.add_argument("-no-obs", dest="obs", action="store_false",
                   help="disable the device counter ring (also the "
                        "carry shape pre-obs checkpoints expect)")
    c.add_argument("-obs-slots", dest="obsslots", type=int, default=256,
                   metavar="N",
                   help="counter-ring depth: per-level rows retained on "
                        "device between fences (wrap loses per-level "
                        "resolution, never totals - rows are cumulative)")
    c.add_argument("-journal", default="", metavar="PATH",
                   help="append-only JSONL run journal (fsync'd per "
                        "event, schema-versioned: obs/schema.py).  "
                        "Defaults to CHECKPOINT.journal.jsonl when "
                        "-checkpoint is set; -recover APPENDS, so an "
                        "interrupted+resumed run has ONE journal.  "
                        "tools/tlcstat.py tails it live")
    c.add_argument("-serve", dest="serve", type=int, default=0,
                   metavar="PORT",
                   help="run the live monitor server on PORT for the "
                        "whole run: /metrics (Prometheus text), "
                        "/events (SSE journal tail, survives "
                        "interrupt+-recover as one stream), /runs "
                        "(registry), /journal (raw; tools/tlcstat.py "
                        "--connect renders it).  python -m "
                        "jaxtlc.obs.serve serves existing journals "
                        "standalone")
    c.add_argument("-phase-timing", dest="phasetiming",
                   action="store_true",
                   help="measured per-level expand/commit walls: the "
                        "supervisor swaps the fused segment dispatch "
                        "for a host-fenced step loop built from the "
                        "same stage closures (bit-for-bit results), "
                        "journaling `phase` events the trace exporter "
                        "renders as measured lanes.  Costs a fence per "
                        "step (PERF.md round 11); unpipelined single-"
                        "device engines only - other paths keep the "
                        "free segment-scope attribution")
    c.add_argument("-trace-out", dest="traceout", default="",
                   metavar="FILE",
                   help="export the run timeline as a Chrome-trace JSON "
                        "(open in ui.perfetto.dev): segment slices, "
                        "per-level expand/commit lanes, checkpoint "
                        "writes, regrow/retry/interrupt markers, "
                        "counter tracks")
    c.add_argument("-xprof", default="", metavar="DIR",
                   help="wrap the check in a jax.profiler trace writing "
                        "to DIR (the ground-truth device timeline; "
                        "view with TensorBoard/XProf)")
    c.add_argument("-narrow", dest="narrow", action="store_true",
                   default=False,
                   help="struct frontend: run on the certified-bound "
                        "NARROWED codec (jaxtlc.analysis.absint): enum "
                        "universes, mask bit counts and sequence caps "
                        "shrink to the certified reachable ranges, "
                        "cutting packed uint32 words through the "
                        "fingerprint/sort/probe path.  Counts and "
                        "verdict are identical to an un-narrowed run "
                        "(fingerprints differ - a different packing); "
                        "the on-device runtime certificate re-verifies "
                        "every claimed bound on every generated state "
                        "and escalates any violation to an error "
                        "verdict.  Refused (baseline layout, with a "
                        "warning) when the bound report cannot be "
                        "certified")
    c.add_argument("-no-narrow", dest="narrow", action="store_false",
                   help="(default) the baseline widened codec layout")
    c.add_argument("-analyze", action="store_true",
                   help="deep preflight: in addition to the default "
                        "spec-IR lints and counter-width arithmetic, "
                        "trace the engine jaxpr and audit hot-body "
                        "purity and donation safety (tracing only - "
                        "no extra XLA compile; python -m "
                        "jaxtlc.analysis runs the same suite "
                        "standalone)")
    c.add_argument("-no-preflight", dest="preflight",
                   action="store_false", default=True,
                   help="skip the preflight analysis suite (the "
                        "escape hatch when a lint is wrong; error-"
                        "severity findings otherwise abort the run "
                        "with a nonzero exit)")
    c.add_argument("-coverage", action="store_true",
                   help="compile per-site coverage counters into the "
                        "kernels (live `coverage` journal events, "
                        "GET /coverage + Prometheus coverage_site_total "
                        "on -serve, MC.out-format end-of-run dump; the "
                        "KubeAPI path additionally renders the full "
                        "host-walker dump for exact MC.out parity)")
    c.add_argument("-simulate", action="store_true",
                   help="randomized simulation instead of exhaustive "
                        "BFS (jaxtlc.sim, the TLC -simulate analog): "
                        "-walkers W device-resident random walks of "
                        "depth -depth N through the same compiled "
                        "spec kernels, each lane a pure function of "
                        "(-sim-seed, lane) - a violation replays "
                        "host-side from the seed alone and renders "
                        "the standard exit-12 trace.  A clean result "
                        "is a SMOKE verdict (sampled, not "
                        "exhaustive); the artifact cache is bypassed. "
                        " Composes with -checkpoint/-recover (the "
                        "(seed, step) cursor checkpoints) and "
                        "-frontend struct runs any spec this way")
    c.add_argument("-depth", type=int, default=100,
                   help="simulation walk depth (transitions per "
                        "walker; TLC's -depth)")
    c.add_argument("-walkers", type=int, default=256,
                   help="simulation walker lanes stepped in one "
                        "vmapped device dispatch")
    c.add_argument("-sim-seed", dest="simseed", type=int, default=0,
                   help="simulation run seed: every walk trajectory "
                        "(and any violation it finds) is an exact "
                        "pure function of this value")
    c.add_argument("-infer", action="store_true",
                   help="inductive invariant inference instead of "
                        "checking (jaxtlc.infer): conjecture up to "
                        "-infer-budget candidate predicates over the "
                        "spec's shapes, kill the ones reachable "
                        "evidence refutes in one vmapped "
                        "predicates-x-states device kernel, certify "
                        "the survivors inductive over the reachable "
                        "set's one-step successors.  Exact evidence "
                        "comes from the reachable-set artifact or a "
                        "host BFS; intractable configs sample "
                        "-walkers x -depth walk states (survivors are "
                        "then 'consistent with evidence only').  "
                        "Exits 12 only when exact evidence refutes a "
                        "cfg-named invariant; requires -frontend "
                        "struct")
    c.add_argument("-infer-budget", dest="inferbudget", type=int,
                   default=64,
                   help="candidate pool cap for -infer (conjectures "
                        "beyond it are counted as dropped in the "
                        "journal)")
    c.add_argument("-liveness", action="store_true",
                   help="check the declared temporal properties even when "
                        "the launch config disables them (E8); above "
                        "the host-path size threshold the device-resident "
                        "liveness engine (edge capture + tensorized "
                        "fixpoint) is picked automatically")
    c.add_argument("-liveness-host", action="store_true",
                   dest="liveness_host",
                   help="force the host-resident liveness path (explicit "
                        "graph construction) regardless of state count")
    c.add_argument("-fairness", default="wf_next",
                   choices=["wf_next", "wf_process"],
                   help="wf_next = the spec's literal WF_vars(Next); "
                        "wf_process = per-process weak fairness.  The "
                        "fairness unit of wf_process is BY CONVENTION the "
                        "FIRST bound parameter of each action (e.g. "
                        "RequestVote(self, voter) is weakly fair per "
                        "`self`); specs whose actions bind a non-process "
                        "value first get a wrong partition - reorder the "
                        "parameters or stay with wf_next")
    c.add_argument("-nodeadlock", action="store_true")
    c.add_argument("-noTool", action="store_true",
                   help="plain text output (no @!@!@ framing)")
    c.add_argument("-traceExpressions", default="", metavar="FILE",
                   help="trace-explorer expression file (one TLA+ "
                        "expression per line, `Name == Expr` to name it); "
                        "each is evaluated in every counterexample trace "
                        "state and printed as an extra conjunct (the "
                        "Toolbox MC_TE capability)")
    c.add_argument("-mutation", default="",
                   help="self-test: run with a deliberately broken "
                        "transition rule (e.g. delete_noop) to exercise "
                        "violation detection + trace reconstruction")
    args = p.parse_args(argv)
    _select_platform(args.workers)
    if args.nocompilecache:
        os.environ["JAXTLC_COMPILE_CACHE"] = "off"
    elif args.compilecache:
        os.environ["JAXTLC_COMPILE_CACHE"] = args.compilecache
    if args.cmd == "check":
        return run_check(CheckRequest.from_args(args)).exit_code
    return 1


def _select_platform(workers: str) -> None:
    """Apply the platform choice via jax.config BEFORE backend init.

    In the tunnel environment the JAX_PLATFORMS env var is applied too
    late (the baked sitecustomize registers the tunnel PJRT plugin at
    interpreter start), and with the tunnel down even `JAX_PLATFORMS=cpu`
    then hangs inside PJRT init; updating jax.config before the first
    device query is the reliable escape.  `-workers cpu` or a cpu env
    request both take this path; anything else keeps the default
    (device) platform, matching TLC's `-workers` being a plain knob.
    """
    if workers == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


if __name__ == "__main__":
    sys.exit(main())
