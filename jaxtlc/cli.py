"""Command-line interface - the TLC invocation contract (E14).

Replaces `java tlc2.TLC -config MC.cfg ...` for the KubeAPI spec family:

    python -m jaxtlc.cli check /path/to/Model_1/MC.cfg \\
        [-workers tpu] [-fpset JaxFPSet] [-fp 51] [-sharded N] \\
        [-chunk 1024] [-nodeadlock] [-noTool]

Reads the unmodified reference artifacts (MC.cfg + sibling MC.tla + the
toolbox .launch if present - BASELINE.json's `-fpset JaxFPSet -workers tpu`
contract), runs the exhaustive check on the fused device engine (or the
sharded multi-device engine with -sharded), and emits the TLC structured
log protocol.  On violation it re-runs in host mode to reconstruct the
counterexample trace and prints it TLC-style with PlusCal action labels.

Exit codes: 0 = no error; 12 = safety violation (TLC's EC.ExitStatus
convention for violations); 13 = liveness violation; 75 = interrupted
(SIGTERM/SIGINT) OR capacity-exhausted (the degradation ladder's final
rung) with a final checkpoint written - resume with -recover;
1 = usage/config error (including non-regrowable codec slot overflow).

Robustness (the resil supervisor wraps the KubeAPI-path engines):
capacity exhaustion walks a degradation ladder instead of aborting -
-auto-grow (default) doubles a saturated fpset/queue/route resource
after a probe allocation confirms it fits; when the probe is denied,
-spill (default auto) activates the host-RAM fingerprint spill tier so
the run completes inside the device memory it has; then chunk shrink;
then checkpoint + exit 75.  -retry N retries segments around transient
device errors (RESOURCE_EXHAUSTED is classified as deterministic and
goes to the ladder, never the retry budget); -checkpoint writes
CRC-verified generation-numbered snapshots (spilling runs pair each
with a host-tier .spill sibling) and -recover loads the newest intact
one (auto-grown geometry and the host tier travel with the checkpoint).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

from . import __version__
from .config import ModelConfig
from .engine.fingerprint import DEFAULT_SEED
from .frontend.model import RunSpec, resolve
from .io.tlc_log import TLCLog


def _run_check(args) -> int:
    try:
        spec: RunSpec = resolve(
            args.config,
            workers=args.workers,
            fp_index=args.fp,
            check_deadlock=not args.nodeadlock,
            frontend=args.frontend,
        )
    except (ValueError, OSError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    from .frontend.model import GenRunSpec, StructRunSpec

    if isinstance(spec, GenRunSpec):
        return _run_check_gen(args, spec)
    if isinstance(spec, StructRunSpec):
        return _run_check_struct(args, spec)
    from .frontend.model import KNOWN_PROPERTIES

    unknown = [q for q in spec.properties if q not in KNOWN_PROPERTIES]
    if unknown:
        print(
            f"Error: unknown PROPERTY {', '.join(unknown)} "
            f"(supported: {', '.join(KNOWN_PROPERTIES)})",
            file=sys.stderr,
        )
        return 1
    if args.mutation:
        spec.model = dataclasses.replace(spec.model, mutation=args.mutation)
    if args.recover and not args.checkpoint:
        print("Error: -recover requires -checkpoint PATH", file=sys.stderr)
        return 1

    log = TLCLog(tool_mode=not args.noTool,
                 **_render_sources(args.config, spec.spec_name))
    import jax

    device = str(jax.devices()[0])
    log.version(__version__)
    log.banner(spec.fp_index, DEFAULT_SEED, spec.workers, device)
    log.sany(*_sany_inputs(args.config, spec.spec_name))
    log.starting()
    log.computing_init()

    _open_journal(
        args, workload=spec.spec_name,
        engine=("hybrid" if args.fpset == "DiskFPSet"
                else "sharded" if args.sharded else "single"),
        device=device,
        params=dict(chunk=args.chunk, queue_capacity=args.qcap,
                    fp_capacity=args.fpcap, sharded=args.sharded,
                    pipeline=args.pipeline,
                    obs_slots=_obs_slots(args)),
    )

    def _kubeapi_preflight(deep):
        from .analysis.preflight import preflight_kubeapi

        return preflight_kubeapi(
            spec.model, fp_capacity=args.fpcap, chunk=args.chunk,
            queue_capacity=args.qcap, deep=deep,
        )

    rc = _preflight_gate(args, log, _kubeapi_preflight)
    if rc is not None:
        return rc
    t0 = time.time()
    from .resil import SlotOverflowError

    sup = None  # SupervisedResult when the resil supervisor ran
    try:
        with _xprof(args):
            r, sup = _dispatch_check(args, spec, log)
    except SlotOverflowError as e:
        log.msg(1000, f"Run stopped: {e}", severity=1)
        _finish_journal(args, log)
        return 1
    except FileNotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        _finish_journal(args, log)
        return 1
    log.init_done(2 ** spec.model.n_reconcilers)

    if sup is not None and sup.interrupted:
        # the interrupted banner (with the resume command) was already
        # emitted by the supervisor's event hook
        from .resil import EXIT_INTERRUPTED

        log.progress(r.depth, r.generated, r.distinct, r.queue_left)
        log.final_counts(r.generated, r.distinct, r.queue_left)
        _finish_journal(args, log, r=None, sup=sup)
        return EXIT_INTERRUPTED

    from .engine.bfs import (
        VIOL_ASSERT,
        VIOL_DEADLOCK,
        VIOL_ONLYONEVERSION,
        VIOL_TYPEOK,
    )

    violated = r.violation != 0
    liveness_violated = False
    if not violated and (args.liveness or spec.properties):
        from .live.check import check_properties_device, use_device_path
        from .spec.codec import get_codec
        from .spec.pretty import state_to_tla

        props = spec.properties or ["ReconcileCompletes", "CleansUpProperly"]
        device_path = use_device_path(
            r.distinct, args.fairness, args.liveness_host
        )
        log.checking_temporal(
            r.distinct, "device" if device_path else "host"
        )
        if device_path:
            mesh = None
            if args.sharded:
                from jax.sharding import Mesh

                import numpy as np

                mesh = Mesh(np.array(jax.devices()[: args.sharded]),
                            ("fp",))
            results = check_properties_device(
                spec.model, props, chunk=args.chunk,
                state_capacity=args.fpcap, fp_capacity=args.fpcap,
                mesh=mesh,
                spill_path=args.checkpoint or None,
            )
        else:
            from .engine.liveness import build_graph, check_properties

            graph = build_graph(spec.model, chunk=args.chunk)
            results = check_properties(
                spec.model, props, graph=graph,
                fairness=args.fairness,
            )
        decode = get_codec(spec.model).decode
        for res in results:
            if res.holds:
                log.msg(1000, f"Temporal property {res.name} holds "
                              f"(fairness: {args.fairness}).")
                continue
            liveness_violated = True
            log.msg(2116, f"Temporal properties were violated: {res.name} "
                          f"(fairness: {args.fairness})", severity=1)
            idx = 1
            for enc, act in zip(res.prefix, res.prefix_actions):
                log.trace_state(idx, act, state_to_tla(decode(enc), spec.model))
                idx += 1
            log.msg(1000, "-- The following states form a cycle "
                          "(back to the first of them) --")
            for enc, act in zip(res.cycle, res.cycle_actions):
                log.trace_state(idx, act, state_to_tla(decode(enc), spec.model))
                idx += 1
    if violated:
        if r.violation == VIOL_TYPEOK and "TypeOK" in spec.invariants:
            log.invariant_violated("TypeOK")
        elif r.violation == VIOL_ONLYONEVERSION and (
            "OnlyOneVersion" in spec.invariants
        ):
            log.invariant_violated("OnlyOneVersion")
        elif r.violation == VIOL_ASSERT:
            log.assertion_failed("Failure of PlusCal assertion.")
        elif r.violation == VIOL_DEADLOCK and spec.check_deadlock:
            log.deadlock()
        else:
            log.msg(1000, f"Run stopped: {r.violation_name}", severity=1)
        _print_trace(log, spec.model, args.chunk,
                     trace_expr_file=args.traceExpressions,
                     check_deadlock=spec.check_deadlock)
    elif not liveness_violated:
        log.success(r.generated, r.distinct,
                    getattr(r, "actual_fp_collision", None),
                    occupancy=getattr(r, "fp_occupancy", None))
        if args.coverage:
            # full per-expression dump (MC.out:44-1092): re-walk the space
            # with the instrumented evaluator (host-side; slow for large
            # configs - TLC's coverage mode pays a similar tax)
            from .spec.coverage import render_coverage, run_coverage

            cov = run_coverage(spec.model)
            stamp = time.strftime("%Y-%m-%d %H:%M:%S")
            for line in render_coverage(cov, stamp, tool_mode=log.tool):
                log.raw(line)
        else:
            log.coverage(2, r.action_generated, r.action_distinct)

    log.progress(r.depth, r.generated, r.distinct, r.queue_left)
    log.final_counts(r.generated, r.distinct, r.queue_left)
    log.depth(r.depth)
    if r.outdegree is not None:
        log.outdegree(*r.outdegree)
    log.finished(int((time.time() - t0) * 1000))
    _finish_journal(
        args, log, r=r, sup=sup,
        verdict="liveness_violation" if liveness_violated else None,
        wall_s=time.time() - t0,
    )
    if violated:
        return 12
    return 13 if liveness_violated else 0  # TLC liveness exit convention


def _xprof(args):
    """jax.profiler trace context for `-xprof DIR` (the ground-truth
    device timeline; the journal's -trace-out is the cheap host view).
    A no-op context when the flag is off."""
    import contextlib

    if not args.xprof:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(args.xprof)


def _dispatch_check(args, spec, log):
    """Run the KubeAPI-path engine picked by the flags.  Returns
    (CheckResult, SupervisedResult-or-None).

    Dispatch priority: DiskFPSet routes to the host tier even when
    -sharded is given (sharding then means fingerprint-space partitions).
    The resil supervisor wraps the device engines whenever -auto-grow
    (default) or -checkpoint is in play; -no-auto-grow without
    -checkpoint keeps the raw fused single-dispatch path."""
    import jax

    if args.sharded and args.fpset != "DiskFPSet":
        import numpy as np
        from jax.sharding import Mesh

        from .engine.sharded import check_sharded

        mesh = Mesh(np.array(jax.devices()[: args.sharded]), ("fp",))
        if args.checkpoint or args.autogrow:
            from .resil import check_sharded_supervised

            sup = check_sharded_supervised(
                spec.model,
                mesh,
                chunk=args.chunk,
                queue_capacity=args.qcap,
                fp_capacity=args.fpcap,
                route_factor=args.routefactor,
                pipeline=args.pipeline,
                obs_slots=_obs_slots(args),
                opts=_sup_opts(args, log),
            )
            return sup.result, sup
        return check_sharded(
            spec.model,
            mesh,
            chunk=args.chunk,
            queue_capacity=args.qcap,
            fp_capacity=args.fpcap,
            route_factor=args.routefactor,
            pipeline=args.pipeline,
            obs_slots=_obs_slots(args),
        ), None
    if args.fpset == "DiskFPSet":
        # the OffHeapDiskFPSet/DiskStateQueue analog: authoritative dedup +
        # frontier in the native (C++, disk-bounded) host tier.  Composes
        # with -checkpoint (the disk tier's files ARE the snapshot, as in
        # TLC) and with -sharded N (N fingerprint-space partitions - the
        # distributed-fingerprint-server analog, launch:4)
        from .engine.hybrid import check_hybrid

        nparts = max(args.sharded, 1)
        if nparts & (nparts - 1):
            raise FileNotFoundError(
                "-sharded with -fpset DiskFPSet needs a power-of-two "
                f"partition count, got {nparts}"
            )
        return check_hybrid(
            spec.model,
            chunk=args.chunk,
            fp_index=spec.fp_index,
            fp_partitions=nparts,
            ckpt_path=args.checkpoint or None,
            ckpt_every=args.checkpointevery,
            resume=args.recover,
        ), None
    if args.checkpoint or args.autogrow:
        from .resil import check_supervised

        sup = check_supervised(
            spec.model,
            chunk=args.chunk,
            queue_capacity=args.qcap,
            fp_capacity=args.fpcap,
            fp_index=spec.fp_index,
            pipeline=args.pipeline,
            obs_slots=_obs_slots(args),
            opts=_sup_opts(args, log),
        )
        return sup.result, sup
    from .engine.bfs import check

    return check(
        spec.model,
        chunk=args.chunk,
        queue_capacity=args.qcap,
        fp_capacity=args.fpcap,
        fp_index=spec.fp_index,
        pipeline=args.pipeline,
        obs_slots=_obs_slots(args),
    ), None


def _preflight_gate(args, log, build_report):
    """Run the preflight suite before a check (ISSUE 6 pipeline).

    -no-preflight skips entirely; -analyze runs the deep mode (adds
    the engine jaxpr purity trace - tracing only, no XLA compile).
    Findings journal as schema-validated `analysis` events and render
    as TLC-style warning banners (derived views of the same events, so
    they cannot disagree); a clean preflight is silent.  Returns the
    nonzero exit code on error-severity findings, None to proceed."""
    if not args.preflight:
        return None
    from .analysis.report import emit_to_journal
    from .obs.views import render_tlc_event

    try:
        report = build_report(args.analyze)
    except Exception as e:  # a broken lint must never block a run
        log.msg(1000, f"Preflight analysis skipped: {e}", severity=1)
        return None
    journal = getattr(args, "_journal", None)

    def on_event(kind, info):
        import time as _time

        from .obs.schema import SCHEMA_VERSION

        render_tlc_event(log, {"v": SCHEMA_VERSION, "t": _time.time(),
                               "event": kind, **info})

    emit_to_journal(journal, report, on_event=on_event)
    if report.errors:
        if journal is not None:
            journal.event("final", verdict="error", generated=0,
                          distinct=0, depth=0, queue=0, wall_s=0.0,
                          interrupted=False)
        log.msg(1000, "Preflight analysis found error-severity "
                      "findings; run aborted (-no-preflight to "
                      "override).", severity=1)
        _finish_journal(args, log)
        return report.exit_code
    return None


def _sup_opts(args, log):
    """SupervisorOptions from the CLI flags.  Every supervisor event is
    written to the run journal FIRST (the single source of truth), then
    the TLC-style banner is rendered as a derived view of that journal
    event (obs.views.render_tlc_event) - the 2200 Progress line and the
    checkpoint/recovery/regrow banners cannot drift from what the
    journal records."""
    from .obs.views import render_tlc_event
    from .resil import FaultPlan, SupervisorOptions

    journal = getattr(args, "_journal", None)
    resume_cmd = _resume_command(args)

    def on_event(kind, info):
        if journal is not None:
            ev = journal.event(kind, **info)
        else:
            import time as _time

            from .obs.schema import SCHEMA_VERSION

            ev = {"v": SCHEMA_VERSION, "t": _time.time(),
                  "event": kind, **info}
        render_tlc_event(log, ev, resume_cmd=resume_cmd)

    return SupervisorOptions(
        auto_grow=args.autogrow,
        max_regrow=args.maxregrow,
        retries=args.retry,
        ckpt_path=args.checkpoint or None,
        ckpt_every=args.checkpointevery,
        resume=args.recover,
        spill=args.spill,
        phase_timing=args.phasetiming,
        faults=FaultPlan.parse(args.faults) if args.faults else None,
        on_event=on_event,
    )


def _obs_slots(args) -> int:
    """Counter-ring depth in effect: -no-obs disables the device tier
    entirely (the A/B baseline; also the shape pre-obs checkpoints
    expect), otherwise -obs-slots levels of history ride the carry."""
    return args.obsslots if args.obs else 0


def _open_journal(args, workload: str, engine: str, device: str,
                  params: dict):
    """Create the run journal and stamp the manifest.

    Path resolution: -journal PATH wins; else a -checkpoint run
    journals beside its snapshots (PATH.journal.jsonl) so preemption
    and -recover find it; else the journal is in-memory only (still
    powers -trace-out).  A -recover run APPENDS and stamps run_resume:
    one continuous journal per logical run, not one per attempt."""
    from . import __version__ as _v
    from .obs.journal import RunJournal

    path = args.journal or (
        args.checkpoint + ".journal.jsonl" if args.checkpoint else ""
    )
    if not path and args.serve:
        # the monitor serves journal FILES; an unjournaled -serve run
        # gets one beside the temp dir (printed below via the server)
        import tempfile

        path = os.path.join(
            tempfile.gettempdir(),
            f"jaxtlc-{os.getpid()}.journal.jsonl",
        )
    resume = bool(args.recover and path and os.path.exists(path))
    j = RunJournal(path or None, resume=resume)
    if resume:
        j.event("run_resume", version=_v, path=path)
    else:
        j.event("run_start", version=_v, workload=workload,
                engine=engine, device=device, params=params)
    args._journal = j
    if args.serve:
        # live ops plane: /metrics + /events (SSE) + /runs over this
        # run's journal directory for the run's whole lifetime
        from .obs.serve import start_server

        args._server = start_server(
            os.path.dirname(os.path.abspath(path)) or ".",
            port=args.serve,
        )
        print(f"jaxtlc monitor at {args._server.url} "
              "(/runs /metrics /events /journal)", file=sys.stderr)
    return j


def _finish_journal(args, log, r=None, sup=None, verdict: str = None,
                    wall_s: float = 0.0) -> None:
    """Close out the journal: the final event (when the supervisor did
    not already emit one), the violation record, and the -trace-out
    export (reading the WHOLE journal file so a resumed run's trace
    covers both attempts)."""
    j = getattr(args, "_journal", None)
    if j is None:
        return
    try:
        if r is not None and r.violation != 0:
            j.event("violation", code=int(r.violation),
                    name=r.violation_name)
        if verdict == "liveness_violation":
            j.event("violation", code=13,
                    name="Temporal properties were violated")
        if sup is None and r is not None:
            v = verdict or ("violation" if r.violation != 0 else "ok")
            j.event("final", verdict=v, generated=r.generated,
                    distinct=r.distinct, depth=r.depth,
                    queue=r.queue_left, wall_s=round(wall_s, 6),
                    interrupted=False)
        if args.traceout:
            from .obs.journal import read as read_journal
            from .obs.trace import export_chrome_trace

            events = read_journal(j.path, validate=False) if j.path \
                else j.events
            n = export_chrome_trace(events, args.traceout)
            j.event("trace_export", path=args.traceout, events=n)
            log.msg(1000, f"Timeline trace written to {args.traceout} "
                          f"({n} events; open in ui.perfetto.dev).")
    finally:
        j.close()
        args._journal = None
        server = getattr(args, "_server", None)
        if server is not None:
            server.shutdown()
            args._server = None


def _resume_command(args) -> str:
    """The command an interrupted run prints (geometry travels inside the
    checkpoint meta, so only the run-shaping flags need repeating)."""
    parts = ["python -m jaxtlc.cli check", args.config]
    if args.checkpoint:
        parts += ["-checkpoint", args.checkpoint, "-recover"]
    if args.chunk != 1024:
        parts += ["-chunk", str(args.chunk)]
    if args.sharded:
        parts += ["-sharded", str(args.sharded)]
    if args.pipeline:
        parts += ["-pipeline"]  # checkpoints only resume in the same mode
    if args.frontend != "auto":
        parts += ["-frontend", args.frontend]
    if not args.checkpoint:
        return ("re-run from scratch (no -checkpoint was set): "
                + " ".join(parts))
    return " ".join(parts)


def _render_sources(cfg_path: str, spec_name: str) -> dict:
    """Rendering inputs derived from the model directory (M4): the
    action-line table scanned from the spec's committed translation, and
    the Toolbox .pmap (generated-TLA -> PlusCal source map) when present."""
    import os

    out = {}
    model_dir = os.path.dirname(os.path.abspath(cfg_path))
    tla = os.path.join(model_dir, f"{spec_name}.tla")
    if os.path.exists(tla):
        from .io.tlc_log import action_lines_from_spec

        out["action_lines"] = action_lines_from_spec(tla)
    pmap_path = os.path.join(
        os.path.dirname(model_dir), f"{spec_name}.tla.pmap"
    )
    if os.path.exists(pmap_path):
        from .frontend.pmap import PmapError, parse_pmap_file

        try:
            out["pcal_map"] = parse_pmap_file(pmap_path)
        except PmapError:
            pass  # a corrupt pmap must not break the run (Toolbox parity)
    return out


def _sany_inputs(cfg_path: str, spec_name: str):
    """Files actually read + modules resolved, for the SANY log section."""
    import os

    model_dir = os.path.dirname(os.path.abspath(cfg_path))
    files, modules = [], []
    # TLC's order (MC.out:8-24): the root MC.tla parses first, semantic
    # processing finishes with the root module last
    mc = os.path.join(model_dir, "MC.tla")
    if os.path.exists(mc):
        files.append(mc)
    sp = os.path.join(model_dir, f"{spec_name}.tla")
    if os.path.exists(sp):
        files.append(sp)
        modules.append(spec_name)
    if os.path.exists(mc):
        modules.append("MC")
    return files, modules


def _run_check_gen(args, spec) -> int:
    """Check a generic-frontend spec (E1): device engine + host liveness.

    -sharded runs the gen lane kernel through the mesh engine (the same
    fp-space partition + all_to_all routing as the KubeAPI path);
    -checkpoint/-recover snapshot the whole sharded carry (a 1-device
    mesh when -sharded is not given), mirroring TLC applying its
    distribution/checkpoint machinery to any spec."""
    from .gen import oracle as go
    from .gen.engine import check_gen

    g = spec.genspec

    def props():
        for name, (p_ast, q_ast) in g.properties.items():
            yield name, p_ast, q_ast, None

    def check():
        if not (args.sharded or args.checkpoint):
            return check_gen(
                g,
                chunk=args.chunk,
                queue_capacity=args.qcap,
                fp_capacity=args.fpcap,
                fp_index=spec.fp_index,
                check_deadlock=spec.check_deadlock,
            )
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from .engine.sharded import (
            check_sharded,
            check_sharded_with_checkpoints,
            gen_backend,
        )

        n_dev = args.sharded or 1
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("fp",))
        backend = gen_backend(g)
        kw = dict(
            chunk=args.chunk,
            queue_capacity=args.qcap,
            fp_capacity=args.fpcap,
            route_factor=args.routefactor,
            backend=backend,
            pipeline=args.pipeline,
            obs_slots=_obs_slots(args),
        )
        if args.checkpoint:
            meta_config = {
                "spec": spec.spec_name,
                "constants": {
                    k: sorted(v) if isinstance(v, frozenset) else v
                    for k, v in g.constants.items()
                },
            }
            return check_sharded_with_checkpoints(
                None, mesh, ckpt_path=args.checkpoint,
                ckpt_every=args.checkpointevery, resume=args.recover,
                meta_config=meta_config, **kw,
            )
        return check_sharded(None, mesh, **kw)

    def leads_to(name, p, q, distinct=0):
        from .live.check import check_leads_to_device, use_device_path

        if use_device_path(distinct, args.fairness, args.liveness_host):
            mesh = None
            if args.sharded:
                import jax
                import numpy as np
                from jax.sharding import Mesh

                mesh = Mesh(np.array(jax.devices()[: args.sharded]),
                            ("fp",))
            return check_leads_to_device(
                g, p, q, name, chunk=args.chunk,
                state_capacity=args.fpcap, fp_capacity=args.fpcap,
                mesh=mesh, spill_path=args.checkpoint or None,
            )
        return go.check_leads_to(g, p, q, name, fairness=args.fairness)

    kit = _InterpKit(
        kind="generic",
        extra_unsupported=(
            ("-nodeadlock with -sharded/-checkpoint",
             (args.sharded or args.checkpoint)
             and not spec.check_deadlock),
        ),
        check=lambda: (check(), None),
        init_count=lambda: 1,
        properties=props,
        check_leads_to=leads_to,
        fairness_label=args.fairness,
        state_to_tla=lambda st: go.state_to_tla(g, st),
        state_env=lambda st: go.state_env(g, st),
        violation_trace=lambda: go.violation_trace(
            g, check_deadlock=spec.check_deadlock
        ),
        coverage=lambda: _gen_coverage_lines(spec, g),
        preflight=lambda deep: _gen_preflight(args, g, deep),
    )
    return _run_check_interp(args, spec, kit)


def _gen_preflight(args, g, deep):
    from .analysis.preflight import preflight_gen

    return preflight_gen(g, fp_capacity=args.fpcap, deep=deep)


def _gen_coverage_lines(spec, g):
    from .gen.coverage import coverage_walk, render_coverage

    text = ""
    if spec.tla_path:
        try:
            with open(spec.tla_path) as f:
                text = f.read()
        except OSError:
            pass
    init_count, cov = coverage_walk(g, text)
    return render_coverage(
        spec.spec_name, init_count, cov,
        time.strftime("%Y-%m-%d %H:%M:%S"),
    )


def _run_check_struct(args, spec) -> int:
    """Check a structural-frontend spec (E1): the full-module path that
    runs specs outside the gen subset - the reference's own KubeAPI.tla
    included.  The LaneCompiler step is a first-class engine kernel now:
    struct runs ride the production engines - segmented + supervised by
    default (auto-regrow, checkpoints, SIGTERM drain), mesh-sharded
    with -sharded - with the persistent step-compile cache warm-starting
    repeated runs.  Host graph for liveness, host re-run for traces;
    same log protocol and exit conventions."""
    from .struct import oracle as so
    from .struct.backend import struct_meta_config
    from .struct.cache import get_backend
    from .struct.engine import check_struct, check_struct_sharded

    sm = spec.structmodel
    system = sm.system
    if args.recover and not args.checkpoint:
        print("Error: -recover requires -checkpoint PATH", file=sys.stderr)
        return 1
    log_holder = []

    def check():
        log = log_holder[0]
        ckd = spec.check_deadlock
        kw = dict(chunk=args.chunk, queue_capacity=args.qcap,
                  fp_capacity=args.fpcap)
        if args.sharded:
            import numpy as np
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()[: args.sharded]), ("fp",))
            if args.checkpoint or args.autogrow:
                from .resil import check_sharded_supervised

                sup = check_sharded_supervised(
                    None, mesh, backend=get_backend(sm, ckd),
                    meta_config=struct_meta_config(sm),
                    route_factor=args.routefactor,
                    pipeline=args.pipeline,
                    obs_slots=_obs_slots(args),
                    opts=_sup_opts(args, log), **kw,
                )
                return sup.result, sup
            return check_struct_sharded(
                sm, mesh, route_factor=args.routefactor,
                check_deadlock=ckd, pipeline=args.pipeline,
                obs_slots=_obs_slots(args), **kw,
            ), None
        if args.checkpoint or args.autogrow:
            from .resil import check_supervised

            sup = check_supervised(
                None, fp_index=spec.fp_index,
                backend=get_backend(sm, ckd),
                meta_config=struct_meta_config(sm), check_deadlock=ckd,
                pipeline=args.pipeline,
                obs_slots=_obs_slots(args),
                opts=_sup_opts(args, log), **kw,
            )
            return sup.result, sup
        return check_struct(
            sm, fp_index=spec.fp_index, check_deadlock=ckd,
            pipeline=args.pipeline, obs_slots=_obs_slots(args), **kw,
        ), None

    def props():
        for name in spec.properties:
            ast = sm.properties[name]
            if ast[0] != "leadsto" or ast[1][0] == "box":
                yield name, None, None, (
                    "only plain P ~> Q is checked on the structural path"
                )
                continue
            yield name, ast[1], ast[2], None

    def action_order():
        # MC.out prints actions in module-definition order; lane labels
        # ARE definition names, so def_order is the rendering order
        names = set(get_backend(sm, spec.check_deadlock).labels)
        ordered = [n for n in sm.module.def_order if n in names]
        return ordered + [n for n in sorted(names) if n not in ordered]

    kit = _InterpKit(
        kind="structural",
        # the structural liveness graph is wf_next-only so far
        extra_unsupported=(
            ("-fairness wf_process", args.fairness == "wf_process"),
        ),
        check=check,
        # lazy: Init enumeration is real work on struct specs and must
        # not run when the flags are about to be rejected
        init_count=lambda: len(system.initial_states()),
        properties=props,
        check_leads_to=lambda name, p, q, **_kw: so.check_leads_to(
            system, p, q, name
        ),
        fairness_label="wf_next",
        state_to_tla=lambda st: so.state_to_tla(system, st),
        state_env=lambda st: so.state_env(system, st),
        violation_trace=lambda: so.violation_trace(
            system, sm.invariants, check_deadlock=spec.check_deadlock
        ),
        action_order=action_order,
        preflight=lambda deep: _struct_preflight(args, spec, sm, deep),
    )
    return _run_check_interp(args, spec, kit, log_holder=log_holder)


def _struct_preflight(args, spec, sm, deep):
    from .analysis.preflight import preflight_struct

    backend = None
    if deep:
        # the same memoized backend the run is about to use: the deep
        # audit adds a jaxpr trace, never a second lane compile
        from .struct.cache import get_backend

        backend = get_backend(sm, spec.check_deadlock)
    return preflight_struct(
        sm, fp_capacity=args.fpcap, chunk=args.chunk,
        queue_capacity=args.qcap, check_deadlock=spec.check_deadlock,
        deep=deep, backend=backend,
    )


class _InterpKit:
    """Everything the shared interpreted-spec runner needs from a
    frontend: one object so the gen/struct runners cannot drift."""

    def __init__(self, kind, extra_unsupported, check, init_count,
                 properties, check_leads_to, fairness_label,
                 state_to_tla, state_env, violation_trace,
                 coverage=None, action_order=None, preflight=None):
        self.kind = kind
        self.extra_unsupported = extra_unsupported
        self.check = check  # () -> (CheckResult, SupervisedResult | None)
        self.init_count = init_count
        self.properties = properties
        self.check_leads_to = check_leads_to
        self.fairness_label = fairness_label
        self.state_to_tla = state_to_tla
        self.state_env = state_env
        self.violation_trace = violation_trace
        self.coverage = coverage  # () -> dump lines, or None
        self.action_order = action_order  # () -> coverage line order
        self.preflight = preflight  # (deep) -> AnalysisReport, or None


def _run_check_interp(args, spec, kit: "_InterpKit",
                      log_holder: list = None) -> int:
    """Shared runner for the interpreted frontends (gen + struct): the
    KubeAPI-engine knobs are rejected, the device engine checks safety,
    the host graph checks liveness, and violations re-run on the host
    interpreter for the trace.  TLC log protocol + exit conventions."""
    unsupported = [
        flag for flag, on in (
            ("-fpset DiskFPSet", args.fpset != "JaxFPSet"),
            ("-mutation", args.mutation),
            *kit.extra_unsupported,
        ) if on
    ]
    if unsupported:
        print(
            f"Error: {', '.join(unsupported)} not supported for "
            f"{kit.kind}-frontend specs yet",
            file=sys.stderr,
        )
        return 1
    log = TLCLog(tool_mode=not args.noTool)
    if log_holder is not None:
        log_holder.append(log)
    import jax

    device = str(jax.devices()[0])
    log.version(__version__)
    log.banner(spec.fp_index, DEFAULT_SEED, spec.workers, device)
    log.sany(*_sany_inputs(args.config, spec.spec_name))
    log.starting()
    log.computing_init()
    _open_journal(
        args, workload=spec.spec_name,
        engine="sharded" if args.sharded else "single",
        device=device,
        params=dict(chunk=args.chunk, queue_capacity=args.qcap,
                    fp_capacity=args.fpcap, sharded=args.sharded,
                    pipeline=args.pipeline, frontend=kit.kind,
                    obs_slots=_obs_slots(args)),
    )
    if kit.preflight is not None:
        rc = _preflight_gate(args, log, kit.preflight)
        if rc is not None:
            return rc
    t0 = time.time()
    from .resil import SlotOverflowError

    try:
        with _xprof(args):
            r, sup = kit.check()
    except SlotOverflowError as e:
        log.msg(1000, f"Run stopped: {e}", severity=1)
        _finish_journal(args, log)
        return 1
    except FileNotFoundError as e:
        print(f"Error: {e}", file=sys.stderr)
        _finish_journal(args, log)
        return 1
    n_init = kit.init_count()
    log.init_done(n_init)
    if sup is not None and sup.interrupted:
        # the interrupted banner (with the resume command) was emitted
        # by the supervisor's event hook
        from .resil import EXIT_INTERRUPTED

        log.progress(r.depth, r.generated, r.distinct, r.queue_left)
        log.final_counts(r.generated, r.distinct, r.queue_left)
        _finish_journal(args, log, r=None, sup=sup)
        return EXIT_INTERRUPTED
    violated = r.violation != 0
    liveness_violated = False
    if not violated and spec.properties:
        from .live.check import use_device_path

        log.checking_temporal(
            r.distinct,
            "device" if kit.kind == "generic" and use_device_path(
                r.distinct, args.fairness, args.liveness_host
            ) else "host",
        )
        for name, p_ast, q_ast, skip in kit.properties():
            if skip is not None:
                log.msg(1000, f"Temporal property {name} skipped: "
                              f"{skip}.", severity=1)
                continue
            res = kit.check_leads_to(name, p_ast, q_ast,
                                     distinct=r.distinct)
            if res.holds:
                log.msg(1000, f"Temporal property {name} holds "
                              f"(fairness: {kit.fairness_label}).")
                continue
            liveness_violated = True
            log.msg(2116, f"Temporal properties were violated: {name}",
                    severity=1)
            idx = 1
            for st in res.lasso_prefix:
                log.trace_state(idx, None, kit.state_to_tla(st))
                idx += 1
            log.msg(1000, "-- The following states form a cycle "
                          "(back to the first of them) --")
            for st in res.lasso_cycle:
                log.trace_state(idx, None, kit.state_to_tla(st))
                idx += 1
    if violated:
        log.msg(2110 if r.violation >= 100 else 1000,
                r.violation_name, severity=1)
        found = kit.violation_trace()
        if found is None:
            log.msg(1000, "Violation was not reproducible in host mode",
                    severity=1)
        else:
            expr_rows = None
            if args.traceExpressions:
                # trace-explorer re-evaluation over interpreted states
                from .spec.texpr import (
                    TexprError,
                    eval_over_envs,
                    parse_expressions,
                )

                try:
                    with open(args.traceExpressions) as f:
                        exprs = parse_expressions(f.read())
                    expr_rows = eval_over_envs(
                        exprs,
                        [kit.state_env(st) for st, _ in found[1]],
                    )
                except (OSError, TexprError) as e:
                    log.msg(1000, f"Trace expressions skipped: {e}",
                            severity=1)
            for i, (st, act) in enumerate(found[1], start=1):
                head = (f"State {i}: <Initial predicate>" if act is None
                        else f"State {i}: <{act}>")
                text = kit.state_to_tla(st)
                if expr_rows is not None:
                    from .spec.pretty import value_to_tla

                    text += "".join(
                        f"\n/\\ {res.name} = "
                        + (f"<evaluation failed: {res.value}>"
                           if res.failed else value_to_tla(res.value))
                        for res in expr_rows[i - 1]
                    )
                log.msg(2217, head + "\n" + text, severity=1)
    elif not liveness_violated:
        log.success(r.generated, r.distinct,
                    getattr(r, "actual_fp_collision", None),
                    occupancy=getattr(r, "fp_occupancy", None))
        if args.coverage and kit.coverage is not None:
            # full per-expression dump: host re-walk with instrumented
            # evaluation, the KubeAPI path's discipline applied to the
            # generic frontend (slow for large configs, like TLC's own
            # coverage mode)
            log.coverage_gen_dump(kit.coverage())
        else:
            act_gen, act_dist = r.action_generated, r.action_distinct
            if kit.action_order is not None:
                # per-action lines in module-definition (MC.out) order,
                # zero-fire actions printed 0:0 exactly as TLC does
                order = kit.action_order()
                act_gen = {a: act_gen.get(a, 0) for a in order}
                act_dist = {a: act_dist.get(a, 0) for a in order}
            log.coverage_generic(spec.spec_name, n_init,
                                 act_gen, act_dist)
    log.progress(r.depth, r.generated, r.distinct, r.queue_left)
    log.final_counts(r.generated, r.distinct, r.queue_left)
    log.depth(r.depth)
    log.finished(int((time.time() - t0) * 1000))
    _finish_journal(
        args, log, r=r, sup=sup,
        verdict="liveness_violation" if liveness_violated else None,
        wall_s=time.time() - t0,
    )
    if violated:
        return 12
    return 13 if liveness_violated else 0


def _print_trace(log: TLCLog, model: ModelConfig, chunk: int,
                 trace_expr_file: str = "",
                 check_deadlock: bool = True) -> None:
    from .engine.trace import find_violation_trace
    from .spec.pretty import state_to_tla

    found = find_violation_trace(model, chunk=chunk,
                                 check_deadlock=check_deadlock)
    if found is None:
        log.msg(1000, "Violation was not reproducible in host mode", severity=1)
        return
    _, trace = found
    expr_rows = None
    if trace_expr_file:
        # the Toolbox trace-explorer pass (MC_TE.out slot): evaluate each
        # user expression in every trace state, shown as extra conjuncts.
        # A bad/missing expression file must never lose the trace itself.
        from .spec.pretty import value_to_tla
        from .spec.texpr import TexprError, eval_over_trace, parse_expressions

        try:
            with open(trace_expr_file) as f:
                exprs = parse_expressions(f.read())
            expr_rows = eval_over_trace(exprs, trace, model)
        except (OSError, TexprError) as e:
            log.msg(1000, f"Trace expressions skipped: {e}", severity=1)
    for i, (st, act) in enumerate(trace, start=1):
        text = state_to_tla(st, model)
        if expr_rows is not None:
            text += "".join(
                f"\n/\\ {res.name} = "
                + (f"<evaluation failed: {res.value}>" if res.failed
                   else value_to_tla(res.value))
                for res in expr_rows[i - 1]
            )
        log.trace_state(i, act, text)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="jaxtlc")
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("check", help="exhaustively check a TLC model config")
    c.add_argument("config", help="path to MC.cfg (sibling MC.tla is read)")
    c.add_argument("-workers", default="tpu", help="TLC contract knob")
    c.add_argument("-frontend", default="auto",
                   choices=["auto", "hand", "gen", "struct"],
                   help="spec frontend: auto picks hand-tuned KubeAPI / "
                        "gen-subset / structural as applicable; struct "
                        "forces the full-module structural path (runs "
                        "ANY spec, KubeAPI included)")
    c.add_argument("-fpset", default="JaxFPSet",
                   choices=["JaxFPSet", "DiskFPSet"],
                   help="JaxFPSet = device-resident fingerprint table; "
                        "DiskFPSet = native host tier (disk-bounded, the "
                        "OffHeapDiskFPSet analog)")
    c.add_argument("-fp", type=int, default=None, help="fp polynomial index")
    c.add_argument("-sharded", type=int, default=0, metavar="N",
                   help="run the sharded engine over N devices")
    c.add_argument("-chunk", type=int, default=1024)
    c.add_argument("-pipeline", dest="pipeline", action="store_true",
                   default=False,
                   help="software-pipeline the device engines: commit "
                        "(dedup/enqueue) of block k-1 overlaps expansion "
                        "of block k, with the sharded verdict-return "
                        "all_to_all deferred behind the next routing "
                        "collective.  Bit-for-bit identical counts; for "
                        "maximum overlap run with HALF the unpipelined "
                        "sweet-spot -chunk (PERF.md round 7).  A "
                        "checkpoint records this setting: -recover "
                        "must use the same mode")
    c.add_argument("-no-pipeline", dest="pipeline", action="store_false",
                   help="(default) the fused single-stage step bodies")
    c.add_argument("-routefactor", type=float, default=2.0,
                   help="sharded all_to_all bucket size as a multiple of "
                        "the mean per-owner candidate count (raise after "
                        "a routing-bucket-overflow halt)")
    c.add_argument("-qcap", type=int, default=1 << 15)
    c.add_argument("-fpcap", type=int, default=1 << 20)
    c.add_argument("-checkpoint", default="", metavar="PATH",
                   help="periodic engine snapshots to PATH (TLC checkpoint "
                        "analog); resume with -recover")
    c.add_argument("-checkpointevery", type=int, default=256, metavar="N",
                   help="chunks between checkpoints")
    c.add_argument("-recover", action="store_true",
                   help="resume from -checkpoint PATH (TLC -recover "
                        "analog); the newest intact generation is loaded, "
                        "with fallback past a torn newest file")
    c.add_argument("-auto-grow", dest="autogrow", action="store_true",
                   default=True,
                   help="(default) on fpset/queue/route saturation, double "
                        "the saturated resource, migrate the carry, and "
                        "resume instead of aborting")
    c.add_argument("-no-auto-grow", dest="autogrow", action="store_false",
                   help="disable auto-regrow: capacity exhaustion aborts "
                        "with the sizing hint (the pre-supervisor "
                        "behavior); without -checkpoint this also "
                        "restores the raw fused single-dispatch engine")
    c.add_argument("-spill", dest="spill", action="store_const",
                   const="on", default="auto",
                   help="prefer the host-RAM fingerprint spill tier at "
                        "the FIRST fpset saturation (skip the regrow "
                        "attempt).  Default auto: regrow first, spill "
                        "when the doubled table's probe allocation is "
                        "denied (RESOURCE_EXHAUSTED) or -max-regrow is "
                        "reached.  Cold fingerprints migrate to a host "
                        "store behind an on-device membership filter; "
                        "results stay bit-for-bit exact, at a host "
                        "sync per chunk (PERF.md round 10)")
    c.add_argument("-no-spill", dest="spill", action="store_const",
                   const="off",
                   help="remove the spill rung from the degradation "
                        "ladder: a denied fpset regrow then falls "
                        "through to chunk shrink / checkpoint + exit 75")
    c.add_argument("-max-regrow", dest="maxregrow", type=int, default=8,
                   metavar="N",
                   help="max auto-regrow events per run (each doubles one "
                        "resource, so 8 allows 256x growth)")
    c.add_argument("-retry", type=int, default=2, metavar="N",
                   help="retries per segment around transient device/XLA "
                        "errors (exponential backoff with jitter, "
                        "restoring the last good state)")
    c.add_argument("-faults", default="", metavar="PLAN",
                   help="self-test: deterministic fault plan for the "
                        "supervisor (e.g. 'transient@1,sigterm@3,"
                        "write_fail@2,truncate@1'; tools/chaos.py drives "
                        "this end-to-end)")
    c.add_argument("-compile-cache", dest="compilecache", default="",
                   metavar="DIR",
                   help="persistent XLA compile-cache directory for "
                        "compiled steps (default ~/.cache/jaxtlc/xla, or "
                        "$JAXTLC_COMPILE_CACHE; warm-starts repeated runs "
                        "of the same model - delete the directory to "
                        "clear it)")
    c.add_argument("-no-compile-cache", dest="nocompilecache",
                   action="store_true",
                   help="disable the persistent compile cache for this "
                        "run")
    c.add_argument("-obs", dest="obs", action="store_true", default=True,
                   help="(default) carry the on-device observability "
                        "counter ring: one per-level telemetry row "
                        "(generated/distinct/queue/occupancy/per-action "
                        "counts), read back at segment fences and "
                        "journaled as `level` events.  Pure telemetry: "
                        "results are bit-for-bit identical to -no-obs "
                        "(bench.py --obs-ab gates overhead at <= 2%)")
    c.add_argument("-no-obs", dest="obs", action="store_false",
                   help="disable the device counter ring (also the "
                        "carry shape pre-obs checkpoints expect)")
    c.add_argument("-obs-slots", dest="obsslots", type=int, default=256,
                   metavar="N",
                   help="counter-ring depth: per-level rows retained on "
                        "device between fences (wrap loses per-level "
                        "resolution, never totals - rows are cumulative)")
    c.add_argument("-journal", default="", metavar="PATH",
                   help="append-only JSONL run journal (fsync'd per "
                        "event, schema-versioned: obs/schema.py).  "
                        "Defaults to CHECKPOINT.journal.jsonl when "
                        "-checkpoint is set; -recover APPENDS, so an "
                        "interrupted+resumed run has ONE journal.  "
                        "tools/tlcstat.py tails it live")
    c.add_argument("-serve", dest="serve", type=int, default=0,
                   metavar="PORT",
                   help="run the live monitor server on PORT for the "
                        "whole run: /metrics (Prometheus text), "
                        "/events (SSE journal tail, survives "
                        "interrupt+-recover as one stream), /runs "
                        "(registry), /journal (raw; tools/tlcstat.py "
                        "--connect renders it).  python -m "
                        "jaxtlc.obs.serve serves existing journals "
                        "standalone")
    c.add_argument("-phase-timing", dest="phasetiming",
                   action="store_true",
                   help="measured per-level expand/commit walls: the "
                        "supervisor swaps the fused segment dispatch "
                        "for a host-fenced step loop built from the "
                        "same stage closures (bit-for-bit results), "
                        "journaling `phase` events the trace exporter "
                        "renders as measured lanes.  Costs a fence per "
                        "step (PERF.md round 11); unpipelined single-"
                        "device engines only - other paths keep the "
                        "free segment-scope attribution")
    c.add_argument("-trace-out", dest="traceout", default="",
                   metavar="FILE",
                   help="export the run timeline as a Chrome-trace JSON "
                        "(open in ui.perfetto.dev): segment slices, "
                        "per-level expand/commit lanes, checkpoint "
                        "writes, regrow/retry/interrupt markers, "
                        "counter tracks")
    c.add_argument("-xprof", default="", metavar="DIR",
                   help="wrap the check in a jax.profiler trace writing "
                        "to DIR (the ground-truth device timeline; "
                        "view with TensorBoard/XProf)")
    c.add_argument("-analyze", action="store_true",
                   help="deep preflight: in addition to the default "
                        "spec-IR lints and counter-width arithmetic, "
                        "trace the engine jaxpr and audit hot-body "
                        "purity and donation safety (tracing only - "
                        "no extra XLA compile; python -m "
                        "jaxtlc.analysis runs the same suite "
                        "standalone)")
    c.add_argument("-no-preflight", dest="preflight",
                   action="store_false", default=True,
                   help="skip the preflight analysis suite (the "
                        "escape hatch when a lint is wrong; error-"
                        "severity findings otherwise abort the run "
                        "with a nonzero exit)")
    c.add_argument("-coverage", action="store_true",
                   help="emit the full per-expression coverage dump "
                        "(TLC coverage mode; re-walks the space host-side)")
    c.add_argument("-liveness", action="store_true",
                   help="check the declared temporal properties even when "
                        "the launch config disables them (E8); above "
                        "the host-path size threshold the device-resident "
                        "liveness engine (edge capture + tensorized "
                        "fixpoint) is picked automatically")
    c.add_argument("-liveness-host", action="store_true",
                   dest="liveness_host",
                   help="force the host-resident liveness path (explicit "
                        "graph construction) regardless of state count")
    c.add_argument("-fairness", default="wf_next",
                   choices=["wf_next", "wf_process"],
                   help="wf_next = the spec's literal WF_vars(Next); "
                        "wf_process = per-process weak fairness.  The "
                        "fairness unit of wf_process is BY CONVENTION the "
                        "FIRST bound parameter of each action (e.g. "
                        "RequestVote(self, voter) is weakly fair per "
                        "`self`); specs whose actions bind a non-process "
                        "value first get a wrong partition - reorder the "
                        "parameters or stay with wf_next")
    c.add_argument("-nodeadlock", action="store_true")
    c.add_argument("-noTool", action="store_true",
                   help="plain text output (no @!@!@ framing)")
    c.add_argument("-traceExpressions", default="", metavar="FILE",
                   help="trace-explorer expression file (one TLA+ "
                        "expression per line, `Name == Expr` to name it); "
                        "each is evaluated in every counterexample trace "
                        "state and printed as an extra conjunct (the "
                        "Toolbox MC_TE capability)")
    c.add_argument("-mutation", default="",
                   help="self-test: run with a deliberately broken "
                        "transition rule (e.g. delete_noop) to exercise "
                        "violation detection + trace reconstruction")
    args = p.parse_args(argv)
    _select_platform(args.workers)
    if args.nocompilecache:
        os.environ["JAXTLC_COMPILE_CACHE"] = "off"
    elif args.compilecache:
        os.environ["JAXTLC_COMPILE_CACHE"] = args.compilecache
    if args.cmd == "check":
        return _run_check(args)
    return 1


def _select_platform(workers: str) -> None:
    """Apply the platform choice via jax.config BEFORE backend init.

    In the tunnel environment the JAX_PLATFORMS env var is applied too
    late (the baked sitecustomize registers the tunnel PJRT plugin at
    interpreter start), and with the tunnel down even `JAX_PLATFORMS=cpu`
    then hangs inside PJRT init; updating jax.config before the first
    device query is the reliable escape.  `-workers cpu` or a cpu env
    request both take this path; anything else keeps the default
    (device) platform, matching TLC's `-workers` being a plain knob.
    """
    if workers == "cpu" or os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


if __name__ == "__main__":
    sys.exit(main())
