#!/usr/bin/env python
"""cachectl: operate the incremental re-checking artifact store.

The content-addressed verdict + reachable-set cache (ISSUE 13,
jaxtlc/struct/artifacts.py) lives at ``~/.cache/jaxtlc/artifacts`` (or
``$JAXTLC_ARTIFACT_CACHE``).  This tool is the operator surface:

    python tools/cachectl.py ls                    # list artifacts
    python tools/cachectl.py verify                # full CRC pass
    python tools/cachectl.py gc --max-bytes 10e6   # prune LRU to budget
    python tools/cachectl.py --root DIR ...        # a non-default store
    python tools/cachectl.py --tiny                # tier-1 smoke

``verify`` re-runs every artifact through the exact checks a cache read
performs (CRC32, key echo, format/semver) and exits nonzero when any
fail - the CI guard against bit rot in a long-lived store.  ``gc``
keeps the newest artifacts that fit the byte budget and deletes the
rest (reads never delete; pruning is this command's explicit job).

Engine-free and jax-free: safe to run anywhere, including the tier-1
``--tiny`` smoke, which builds a synthetic store, corrupts one file,
and asserts ls/verify/gc behave.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
)

import numpy as np  # noqa: E402

from jaxtlc.struct.artifacts import ArtifactStore  # noqa: E402


def _store(args) -> ArtifactStore:
    if args.root:
        return ArtifactStore(args.root)
    from jaxtlc.struct.artifacts import get_store

    store = get_store()
    if store is None:
        print("cachectl: artifact cache disabled "
              "(JAXTLC_ARTIFACT_CACHE=off); pass --root DIR",
              file=sys.stderr)
        sys.exit(1)
    return store


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n}B"


def cmd_ls(store: ArtifactStore, out=sys.stdout) -> int:
    rows = store.ls()
    out.write(f"{'tier':8} {'workload':16} {'size':>8} {'age':>8} "
              "key\n")
    now = time.time()
    total = 0
    for r in rows:
        total += r["bytes"]
        age = now - r["mtime"]
        age_s = (f"{age:.0f}s" if age < 120 else f"{age / 60:.0f}m"
                 if age < 7200 else f"{age / 3600:.1f}h")
        out.write(f"{r['tier']:8} {str(r['workload']):16} "
                  f"{_fmt_bytes(r['bytes']):>8} {age_s:>8} "
                  f"{r['key'][:16]}...\n")
    out.write(f"{len(rows)} artifact(s), {_fmt_bytes(total)} total in "
              f"{store.root}\n")
    return 0


def cmd_verify(store: ArtifactStore, out=sys.stdout) -> int:
    rows = store.verify()
    bad = [r for r in rows if not r["ok"]]
    for r in rows:
        mark = "ok     " if r["ok"] else "CORRUPT"
        out.write(f"{mark} {r['tier']:8} {r['key'][:16]}...\n")
    out.write(f"verified {len(rows)} artifact(s): "
              f"{len(rows) - len(bad)} ok, {len(bad)} corrupt\n")
    return 1 if bad else 0


def cmd_gc(store: ArtifactStore, max_bytes: float,
           out=sys.stdout) -> int:
    res = store.gc(int(max_bytes))
    out.write(f"gc: kept {res['kept']} artifact(s) "
              f"({_fmt_bytes(res['bytes'])}), deleted {res['deleted']} "
              f"(budget {_fmt_bytes(int(max_bytes))})\n")
    return 0


def _tiny() -> int:
    """Tier-1 smoke: synthetic store -> ls -> verify (clean + after a
    deliberate corruption) -> gc to a budget.  No engine, no jax."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        store = ArtifactStore(d)
        for i in range(3):
            store.put_verdict(f"{'%02x' % i}" + "ab" * 31, dict(
                workload=f"Tiny{i}", verdict="ok", generated=10 + i,
                distinct=5 + i, depth=3, queue=0, n_init=1,
                action_generated={}, action_distinct={},
                action_order=[], outdegree=None, properties=[],
                wall_s=0.1, created_t=time.time(),
            ))
            time.sleep(0.01)  # distinct mtimes for the LRU order
        states = np.arange(20, dtype=np.uint32).reshape(10, 2)
        store.put_reach("ff" * 32, states, dict(
            workload="TinyR", codec_digest="cd", nbits=40,
            generated=30, distinct=10, depth=4, n_init=1,
            action_generated={}, action_distinct={}, outdegree=None,
        ))
        rows = store.ls()
        assert len(rows) == 4, rows
        assert {r["tier"] for r in rows} == {"verdict", "reach"}
        assert cmd_verify(store) == 0
        # round-trip a read through the real lookup path
        got = store.lookup_reach("ff" * 32)
        assert got is not None and np.array_equal(got[0], states)
        # corrupt one verdict artifact in place: verify must flag it,
        # a lookup must MISS loudly, never answer
        victim = store._path("verdict", "00" + "ab" * 31)
        raw = open(victim).read().replace('"generated": 10',
                                          '"generated": 11')
        with open(victim, "w") as f:
            f.write(raw)
        assert cmd_verify(store) == 1
        warned = []
        assert store.lookup_verdict("00" + "ab" * 31,
                                    warn=warned.append) is None
        assert warned and "corrupt" in warned[0]
        # gc to a budget that keeps only the newest artifacts
        keep = sum(r["bytes"] for r in store.ls()[:2])
        cmd_gc(store, keep)
        assert len(store.ls()) == 2
        s = store.stats()
        assert s["corrupt"] == 1 and s["writes"] == 4, s
    print("cachectl tiny OK: ls/verify/corrupt-detect/gc on a "
          "synthetic store")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cachectl")
    p.add_argument("cmd", nargs="?",
                   choices=["ls", "verify", "gc"],
                   help="ls = list artifacts; verify = full CRC pass "
                        "(nonzero exit on corruption); gc = prune LRU "
                        "artifacts to --max-bytes")
    p.add_argument("--root", default="",
                   help="store directory (default: the process store "
                        "per JAXTLC_ARTIFACT_CACHE)")
    p.add_argument("--max-bytes", type=float, default=64e6,
                   help="gc byte budget (default 64e6)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (ls/verify)")
    p.add_argument("--tiny", action="store_true",
                   help="tier-1 smoke: synthetic store end to end "
                        "(no engine, no jax)")
    args = p.parse_args(argv)
    if args.tiny:
        return _tiny()
    if not args.cmd:
        p.error("command required (ls / verify / gc, or --tiny)")
    store = _store(args)
    if args.json:
        if args.cmd == "ls":
            print(json.dumps(store.ls(), indent=2))
            return 0
        if args.cmd == "verify":
            rows = store.verify()
            print(json.dumps(rows, indent=2))
            return 1 if any(not r["ok"] for r in rows) else 0
    if args.cmd == "ls":
        return cmd_ls(store)
    if args.cmd == "verify":
        return cmd_verify(store)
    return cmd_gc(store, args.max_bytes)


if __name__ == "__main__":
    sys.exit(main())
