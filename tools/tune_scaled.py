"""Quick scaled-workload throughput probe: runs N fused segments of the
engine at a given chunk size and reports the marginal distinct/s."""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
from jax import lax

from jaxtlc.config import scaled_config
from jaxtlc.engine.bfs import make_engine


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--qcap", type=int, default=21)
    ap.add_argument("--fpcap", type=int, default=26)
    ap.add_argument("--steps", type=int, default=64, help="steps per segment")
    ap.add_argument("--segments", type=int, default=6)
    args = ap.parse_args()

    cfg, _ = scaled_config()
    init_fn, _, step_fn = make_engine(
        cfg, chunk=args.chunk, queue_capacity=1 << args.qcap,
        fp_capacity=1 << args.fpcap,
    )

    @jax.jit
    def segment(c):
        return lax.fori_loop(0, args.steps, lambda _, cc: step_fn(cc), c)

    carry = init_fn()
    t0 = time.time()
    compiled = segment.lower(carry).compile()
    print(f"chunk={args.chunk} compile {time.time()-t0:.1f}s dev={jax.devices()[0]}")
    carry = jax.block_until_ready(compiled(carry))  # warm ramp
    prev = int(carry.distinct)
    for s in range(args.segments):
        t0 = time.perf_counter()
        carry = jax.block_until_ready(compiled(carry))
        dt = time.perf_counter() - t0
        d = int(carry.distinct)
        print(f"seg {s}: distinct={d:>9}  +{d-prev:>7}  {(d-prev)/dt/1e3:8.1f}k distinct/s  "
              f"({args.steps} steps in {dt:.2f}s, {dt/args.steps*1e3:.1f} ms/step) viol={int(carry.viol)}")
        prev = d


if __name__ == "__main__":
    main()
