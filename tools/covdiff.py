#!/usr/bin/env python
"""covdiff: diff device coverage against an oracle or a prior run.

CI tooling for the device coverage plane (ISSUE 11): compares the
per-site visit counts of a run against a baseline and exits nonzero on
a COVERAGE REGRESSION - a site the baseline visited that the current
run never reached (the "we stopped exercising that behavior" signal;
raw count drift between runs of different sizes is reported but not
fatal unless --exact).

    python tools/covdiff.py CURRENT BASELINE [--exact]
    python tools/covdiff.py --tiny          # tier-1 self-test

Accepted formats for either side (sniffed by content):
  * a run journal (*.jsonl) - the `coverage` delta events fold into
    cumulative totals (obs.coverage.coverage_from_events); a per-host
    POD journal ({base}.hN.journal.jsonl, jaxtlc.dist) pulls in every
    sibling on disk and folds the merged stream, so the diff runs
    against the pod-global summed site table;
  * a JSON artifact {"sites": {key: count, ...}} (GET /coverage body,
    or a previously saved covdiff --save);
  * a committed TLC MC.out - the coverage section's span lines are
    mapped back to span keys through the generated span table
    (jaxtlc/spec/coverage_spans.py), so the device counters diff
    directly against the reference dump.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, Optional

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
)

_ACTION_RE = re.compile(r"^<(\w+) line .*?>: (\d+):(\d+)$")
_SPAN_RE = re.compile(r"^\s*\|*(line .*? to line .*?) of module \w+: "
                      r"(\d+)(?::\d+)?$")


def _load_mc_out(path: str) -> Dict[str, int]:
    """{site key: count} from a TLC MC.out coverage section, keyed via
    the generated span table (loc -> key)."""
    from jaxtlc.spec.coverage_spans import SPANS

    loc_key = {}
    for _name, _code, _loc, lines in SPANS:
        for _dep, loc, key, _lcode, _hc, _ce in lines:
            loc_key.setdefault(loc, key)
    out: Dict[str, int] = {}
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.rstrip("\n")
            m = _ACTION_RE.match(line.strip())
            if m:
                out[m.group(1)] = int(m.group(3))  # generated count
                continue
            m = _SPAN_RE.match(line)
            if m and m.group(1) in loc_key:
                key = loc_key[m.group(1)]
                if key not in out:  # first (outermost) pairing wins
                    out[key] = int(m.group(2))
    return out


def load_sites(path: str) -> Optional[Dict[str, int]]:
    """Sniff + load a coverage table from any supported format."""
    with open(path, "r", encoding="utf-8") as f:
        head = f.read(4096)
    if "@!@!@STARTMSG" in head or "TLC2" in head:
        return _load_mc_out(path)
    if path.endswith(".jsonl") or head.lstrip().startswith('{"'):
        # journal (one JSON object per line) vs artifact (one object)
        try:
            obj = json.load(open(path, "r", encoding="utf-8"))
            # artifact {"sites": {key: count}} - NOT a one-line journal
            # whose coverage event carries the integer `sites` header
            if isinstance(obj, dict) and isinstance(
                    obj.get("sites"), dict):
                return {k: int(v) for k, v in obj["sites"].items()}
        except json.JSONDecodeError:
            pass
        from jaxtlc.obs import journal as jr
        from jaxtlc.obs.coverage import coverage_from_events
        from jaxtlc.obs.views import merge_journals, pod_sibling_journals

        paths = pod_sibling_journals(path)
        events = (jr.read(paths[0], validate=False)
                  if len(paths) == 1 else
                  merge_journals(*(jr.read(p, validate=False)
                                   for p in paths)))
        cov = coverage_from_events(events)
        return cov["sites"] if cov else None
    return None


def diff(cur: Dict[str, int], base: Dict[str, int],
         exact: bool = False):
    """(regressions, drifts, news): sites the baseline visited that the
    run never reached / count changes / newly visited sites."""
    regressions, drifts, news = [], [], []
    for k, b in sorted(base.items()):
        c = cur.get(k, 0)
        if b > 0 and c == 0:
            regressions.append((k, c, b))
        elif c != b:
            drifts.append((k, c, b))
    for k, c in sorted(cur.items()):
        if c > 0 and base.get(k, 0) == 0:
            news.append((k, c))
    if exact:
        regressions = regressions + drifts
        drifts = []
    return regressions, drifts, news


def _tiny() -> int:
    """Self-test: a synthetic artifact pair must flag exactly the
    seeded regression (wired into tier-1 via tests/test_tools.py)."""
    base = {"A": 10, "A.g0": 10, "A.w0": 8, "B": 3, "B.g0": 3}
    cur_ok = {"A": 12, "A.g0": 12, "A.w0": 9, "B": 5, "B.g0": 5}
    cur_bad = {"A": 12, "A.g0": 12, "A.w0": 9, "B": 0, "B.g0": 0}
    r, d, n = diff(cur_ok, base)
    assert not r and len(d) == 5 and not n, (r, d, n)
    r, d, n = diff(cur_bad, base)
    assert [k for k, *_ in r] == ["B", "B.g0"], r
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "cov.json")
        json.dump({"sites": base}, open(p, "w"))
        assert load_sites(p) == base
        # pod journals: two synthetic per-host siblings must load as
        # ONE summed site table from either host's path (the merged
        # pod stream; partial deltas over disjoint shards add)
        from jaxtlc.obs.journal import RunJournal

        h0 = os.path.join(td, "pod.ckpt.h0.journal.jsonl")
        h1 = os.path.join(td, "pod.ckpt.h1.journal.jsonl")
        with RunJournal(h0) as j:
            j.event("coverage", host=0, visited=2, sites=3,
                    delta={"A": 7, "B": 1})
        with RunJournal(h1) as j:
            j.event("coverage", host=1, visited=1, sites=3,
                    delta={"A": 3, "C": 2})
        want = {"A": 10, "B": 1, "C": 2}
        assert load_sites(h0) == want, load_sites(h0)
        assert load_sites(h1) == want, load_sites(h1)
    print("covdiff tiny OK: regression detection + artifact "
          "round-trip + pod-journal merge")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="covdiff")
    p.add_argument("current", nargs="?",
                   help="journal / artifact / MC.out of the run")
    p.add_argument("baseline", nargs="?",
                   help="journal / artifact / MC.out to diff against")
    p.add_argument("--exact", action="store_true",
                   help="any count change is a regression (same-config "
                        "pinning), not just visited -> unvisited")
    p.add_argument("--save", default="",
                   help="write CURRENT's table as a JSON artifact here")
    p.add_argument("--tiny", action="store_true",
                   help="self-test (no inputs; wired into tier-1)")
    args = p.parse_args(argv)
    if args.tiny:
        return _tiny()
    if not args.current:
        p.error("current coverage input required (or --tiny)")
    cur = load_sites(args.current)
    if cur is None:
        print(f"covdiff: no coverage data in {args.current!r}",
              file=sys.stderr)
        return 2
    if args.save:
        with open(args.save, "w", encoding="utf-8") as f:
            json.dump({"sites": cur}, f, sort_keys=True, indent=1)
        print(f"covdiff: saved {len(cur)} sites to {args.save}")
    if not args.baseline:
        return 0
    base = load_sites(args.baseline)
    if base is None:
        print(f"covdiff: no coverage data in {args.baseline!r}",
              file=sys.stderr)
        return 2
    shared = set(cur) & set(base)
    regressions, drifts, news = diff(cur, base, exact=args.exact)
    print(f"covdiff: {len(shared)} shared sites, "
          f"{len(regressions)} regression(s), {len(drifts)} drift(s), "
          f"{len(news)} newly visited")
    for k, c, b in regressions[:50]:
        print(f"  REGRESSION {k}: {b} -> {c}")
    for k, c, b in drifts[:10]:
        print(f"  drift {k}: {b} -> {c}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
