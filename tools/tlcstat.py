#!/usr/bin/env python
"""tlcstat: one-screen live dashboard over a jaxtlc run journal.

Tails the append-only JSONL journal a run writes (`-journal PATH`, or
`CKPT.journal.jsonl` beside a `-checkpoint`) and renders the numbers an
operator actually wants mid-run: current depth, generated/distinct with
interval rates (the same arithmetic as the TLC 2200 Progress line -
obs.views.interval_rates is shared, so they cannot disagree), queue
depth, fingerprint-table occupancy, a queue-drain ETA, recovery-event
counts, and the last journal event.

    python tools/tlcstat.py RUN.journal.jsonl            # one frame
    python tools/tlcstat.py RUN.journal.jsonl --follow   # live tail
    python tools/tlcstat.py --connect http://HOST:PORT   # remote run
    python tools/tlcstat.py --tiny                       # tier-1 smoke

The dashboard is a pure view of the journal - it opens the file
read-only and never blocks the writer (per-event fsync appends are
atomic at line granularity; a torn trailing line is skipped).
--connect renders the SAME view over a jaxtlc.obs.serve monitor's
/journal endpoint (stdlib urllib), so remote runs get the identical
dashboard; --run NAME selects among the server's registered runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
)

from jaxtlc.obs import journal as jr  # noqa: E402
from jaxtlc.obs.schema import SCHEMA_VERSION  # noqa: E402
from jaxtlc.obs.views import eta_s, interval_rates, phase_totals  # noqa: E402


def _fmt_eta(s) -> str:
    if s is None:
        return "-"
    if s < 90:
        return f"{s:.0f}s"
    if s < 5400:
        return f"{s / 60:.1f}m"
    return f"{s / 3600:.1f}h"


def _last_two(events, kinds):
    """(previous, latest) events of the given kinds (None-padded)."""
    hits = [e for e in events if e["event"] in kinds]
    if not hits:
        return None, None
    return (hits[-2] if len(hits) > 1 else None), hits[-1]


def render(events) -> str:
    """One dashboard frame from a journal event list.  A merged pod
    stream folds its per-host partial `level` rows into pod-global
    rows first (obs.views.fold_pod_levels), so the headline counters
    and rates describe the whole pod; the pod line below keeps the
    per-host view (shard load, fence wait)."""
    from jaxtlc.obs.views import fold_pod_levels

    events = fold_pod_levels(events)
    if not events:
        return "tlcstat: journal is empty (run not started yet?)"
    manifest = next(
        (e for e in events if e["event"] == "run_start"), None
    )
    lines = []
    if manifest is not None:
        p = manifest.get("params", {})
        lines.append(
            f"jaxtlc {manifest['version']}  |  {manifest['workload']} "
            f"({manifest['engine']} engine)  |  {manifest['device']}"
        )
        lines.append(
            f"journal schema v{events[0]['v']} (reader v{SCHEMA_VERSION})"
            f"  chunk={p.get('chunk', '?')}"
            f"  fp_capacity={p.get('fp_capacity', '?')}"
            f"  pipeline={p.get('pipeline', False)}"
            f"  obs_slots={p.get('obs_slots', 0)}"
        )
    # progress source: level events (per-level resolution) when the
    # device ring is on, progress events otherwise
    prev, cur = _last_two(events, ("level",))
    if cur is None:
        prev, cur = _last_two(events, ("progress",))
    if cur is not None:
        spm, dpm = interval_rates(
            (prev["t"], prev["generated"], prev["distinct"])
            if prev is not None else None,
            cur["t"], cur["generated"], cur["distinct"],
        )
        depth = cur.get("level", cur.get("depth", "?"))
        lines.append(
            f"depth {depth}  |  {cur['generated']:,} generated "
            f"({spm:,} s/min)  |  {cur['distinct']:,} distinct "
            f"({dpm:,} ds/min)"
        )
        occ = cur.get("fp_load")
        # with the host spill tier active, distinct states exceed the
        # DEVICE table: the ratio is the logical set vs the hot tier
        spilling = any(e["event"] == "spill" for e in events)
        occ_txt = ""
        if occ is not None:
            occ_txt = (f"  |  fp space {occ:.1%} of device tier "
                       "(spilling)" if spilling
                       else f"  |  fp table {occ:.1%} full")
        lines.append(
            f"queue {cur['queue']:,}" + occ_txt
            + f"  |  ETA (queue drain) {_fmt_eta(eta_s(prev, cur))}"
        )
    counts = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    lines.append(
        f"segments {counts.get('segment', 0)}"
        f"  checkpoints {counts.get('checkpoint', 0)}"
        f"  regrows {counts.get('regrow', 0)}"
        f"  retries {counts.get('retry', 0)}"
        f"  interruptions {counts.get('interrupted', 0)}"
        f"  degrades {counts.get('degrade', 0)}"
    )
    # multi-host pod (jaxtlc.dist): per-host shard-table load + spill
    # bytes from the latest pod stats row of each host, and the fence
    # exchange wall of the slowest host (the fence waits for it)
    from jaxtlc.obs.views import pod_host_gauges

    pod = pod_host_gauges(events)
    if pod is not None:
        hosts = max(e["hosts"] for e in events if e["event"] == "pod")
        # per-host fence-wait column: every host reports its OWN vote/
        # exchange wall, so the skewed host is visible by name (the
        # global fence waits for the slowest one, reported last)
        per = "  ".join(
            f"h{h} shard {g['shard_occupancy']:.1%} "
            f"fence {g['exchange_us'] / 1000:.1f}ms"
            + (f" spill {g['spill_bytes'] / 1024:.0f}KiB"
               if g["spill_bytes"] else "")
            for h, g in sorted(pod.items())
        )
        fence = max(g["exchange_us"] for g in pod.values())
        reshards = sum(1 for e in events if e["event"] == "pod"
                       and e.get("phase") == "reshard")
        lines.append(
            f"pod: {hosts} hosts  |  {per}  |  slowest fence "
            f"{fence / 1000:.1f}ms"
            + (f"  |  reshards {reshards}" if reshards else "")
        )
    # host spill tier: occupancy + hit rate of the most recent spill
    # event (the device tier's cold-fingerprint overflow store)
    sp = next((e for e in reversed(events) if e["event"] == "spill"),
              None)
    if sp is not None:
        probes = max(sp.get("probes", 0), 1)
        lines.append(
            f"spill tier: {sp['spilled']:,} fps host-side "
            f"({sp['spilled'] / max(sp['capacity'], 1):.1%} of "
            f"{sp['capacity']:,})  |  flushes "
            f"{max(counts.get('spill', 1) - 1, 0)}  |  host hit-rate "
            f"{sp.get('hits', 0) / probes:.1%} of {sp.get('probes', 0):,}"
            " probes"
        )
    # simulation tier (jaxtlc.sim): the walk cursor + the sampled
    # distinct estimate of the most recent sim event (a smoke run's
    # whole progress story - walks carry no frontier/queue)
    sim = next((e for e in reversed(events) if e["event"] == "sim"),
               None)
    if sim is not None:
        est = sim.get("distinct_est", 0)
        sat = " (saturated)" if sim.get("fp_saturated") else ""
        lines.append(
            f"sim: {sim['walkers']} walkers  depth "
            f"{sim['steps']}/{sim['depth']}  "
            f"{sim['transitions']:,} transitions  "
            f"~{est:,} distinct sampled{sat}"
        )
    # inference tier (jaxtlc.infer): the candidate funnel of the most
    # recent infer event - conjectured -> killed -> surviving ->
    # certified (an inference run's whole progress story)
    inf = next((e for e in reversed(events) if e["event"] == "infer"),
               None)
    if inf is not None:
        lines.append(
            f"infer: {inf['candidates']} candidates  "
            f"{inf['killed']} killed  {inf['survivors']} survive  "
            f"{inf['certified']} certified  "
            f"[{inf.get('evidence', '?')} x "
            f"{inf.get('n_states', 0):,} states]"
        )
    # state-space reduction (engine.reduce): what symmetry/POR bought
    # the most recent reduced run - the orbit factor the space was
    # divided by and the transitions the ample sets cut pre-dedup
    red = next((e for e in reversed(events) if e["event"] == "reduce"),
               None)
    if red is not None:
        lines.append(
            f"reduction: orbit factor {red['orbit_factor']}x  |  "
            f"{red['states_pruned']:,} transitions POR-pruned "
            f"({red['ample_hit_rate']:.1%} of expansion)  |  "
            f"{red['distinct']:,} distinct representatives"
        )
    # incremental re-checking (struct.artifacts): this run's artifact
    # cache decisions - a hit means the verdict was replayed (or BFS
    # skipped) instead of re-explored
    cache_evs = [e for e in events if e["event"] == "cache"]
    if cache_evs:
        hits = [e for e in cache_evs if e.get("outcome") == "hit"]
        misses = sum(1 for e in cache_evs
                     if e.get("outcome") == "miss")
        tiers = ",".join(sorted({e["tier"] for e in hits})) or "-"
        lines.append(
            f"artifact cache: {len(hits)} hit(s) [{tiers}]  "
            f"{misses} miss(es)  "
            f"last {cache_evs[-1]['tier']}/{cache_evs[-1]['outcome']}"
        )
    # scheduler control plane (serve.scheduler): the service's
    # admission/preempt/breaker decision counts, plus the queue depth
    # of the latest event that carried one
    sched_evs = [e for e in events if e["event"] == "sched"]
    if sched_evs:
        acts = {}
        for e in sched_evs:
            acts[e["action"]] = acts.get(e["action"], 0) + 1
        depth = next((e["queued"] for e in reversed(sched_evs)
                      if "queued" in e), None)
        lines.append(
            "sched: " + "  ".join(
                f"{k} {acts[k]}" for k in
                ("admit", "dispatch", "reject", "expire", "preempt",
                 "requeue", "retry", "quarantine", "cancel")
                if k in acts
            ) + (f"  |  queue {depth}" if depth is not None else "")
        )
    # phase attribution (obs.phases): cumulative measured walls per
    # phase - expand/commit from -phase-timing, device/readback free
    # at every fence
    phases = phase_totals(events)
    if phases:
        lines.append("phase walls: " + "  ".join(
            f"{k} {v:.3f}s" for k, v in sorted(phases.items())
        ))
    # device coverage plane (obs.coverage): visited/total sites + the
    # saturation signal, folded from the journal's coverage deltas
    from jaxtlc.obs.coverage import coverage_from_events

    cov = coverage_from_events(events)
    if cov is not None:
        sat = cov.get("saturated_at_level")
        lines.append(
            f"coverage: {cov['visited']}/{cov['n_sites']} sites visited"
            + (f"  |  SATURATED at level {sat} (no new site since)"
               if sat is not None else "")
        )
    last = events[-1]
    age = time.time() - last["t"]
    lines.append(f"last event: {last['event']} ({age:.1f}s ago)")
    fin = next((e for e in reversed(events) if e["event"] == "final"),
               None)
    if fin is not None:
        lines.append(
            f"VERDICT: {fin['verdict']}  -  {fin['generated']:,} "
            f"generated, {fin['distinct']:,} distinct, depth "
            f"{fin['depth']}, wall {fin['wall_s']:.3f}s"
        )
    width = max(len(x) for x in lines)
    bar = "=" * min(width, 78)
    return "\n".join([bar, *lines, bar])


def _read_maybe_pod(path: str) -> list:
    """Journal events; a per-host pod journal (``{base}.hN``) pulls in
    every sibling on disk and k-way merges them, so pointing tlcstat at
    ANY one host renders the whole pod's dashboard."""
    from jaxtlc.obs.views import merge_journals, pod_sibling_journals

    paths = pod_sibling_journals(path)
    if len(paths) == 1:
        return jr.read(paths[0], validate=False)
    return merge_journals(*(jr.read(p, validate=False) for p in paths))


def _fetch_remote(url: str, run: str = "") -> list:
    """Journal events from a jaxtlc.obs.serve monitor's /journal
    endpoint (the remote-client mode of the same dashboard)."""
    import urllib.request

    endpoint = url.rstrip("/") + "/journal"
    if run:
        import urllib.parse

        endpoint += "?run=" + urllib.parse.quote(run)
    with urllib.request.urlopen(endpoint, timeout=10) as r:
        return [json.loads(line) for line in
                r.read().decode().splitlines() if line.strip()]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tlcstat")
    p.add_argument("journal", nargs="?", help="run journal (JSONL)")
    p.add_argument("--connect", default="", metavar="URL",
                   help="render a REMOTE run from a jaxtlc.obs.serve "
                        "monitor (base URL, e.g. http://host:8790)")
    p.add_argument("--run", default="",
                   help="with --connect: which registered run "
                        "(default: the monitor's most recent)")
    p.add_argument("--follow", action="store_true",
                   help="re-render as the journal grows (ctrl-c exits)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="follow-mode refresh seconds")
    p.add_argument("--tiny", action="store_true",
                   help="smoke: render a synthetic journal end-to-end "
                        "(no engine run; wired into tier-1)")
    args = p.parse_args(argv)

    if args.tiny:
        import tempfile

        from jaxtlc.obs.trace import _tiny_journal

        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "tiny.journal.jsonl")
            _tiny_journal(path)
            frame = render(jr.read(path))
        assert "VERDICT: interrupted" in frame and "ds/min" in frame
        assert "phase walls:" in frame and "expand" in frame
        print(frame)
        print("tlcstat tiny OK")
        return 0

    if args.connect:
        try:
            if not args.follow:
                print(render(_fetch_remote(args.connect, args.run)))
                return 0
            while True:
                frame = render(_fetch_remote(args.connect, args.run))
                sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
                sys.stdout.flush()
                time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        except OSError as e:
            print(f"tlcstat: cannot reach {args.connect!r}: {e}",
                  file=sys.stderr)
            return 1
    if not args.journal:
        p.error("journal path required (or --tiny)")
    if not os.path.exists(args.journal):
        print(f"tlcstat: no journal at {args.journal!r}",
              file=sys.stderr)
        return 1
    if not args.follow:
        print(render(_read_maybe_pod(args.journal)))
        return 0
    try:
        while True:
            frame = render(_read_maybe_pod(args.journal))
            sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
