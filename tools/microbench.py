"""Primitive-cost microbench on the tunneled TPU (design inputs for the
fpset v4 / engine restructure).  Everything runs K times inside one fused
dispatch (see profile_scaled.py for why)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K = 32


def fused_time(name, body, carry, reps=3):
    @jax.jit
    def loop(c):
        return lax.fori_loop(0, K, lambda _, cc: body(cc), c)

    out = jax.block_until_ready(loop(carry))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(loop(carry))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:44s} {best / K * 1e3:9.3f} ms")
    return out


def main():
    rng = np.random.default_rng(0)
    print(f"dev={jax.devices()[0]}")
    n = 245760  # chunk 16384 x 15 lanes
    R = 32768
    cap = 1 << 26

    lo = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    hi = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    idx = jnp.arange(n, dtype=jnp.int32).astype(jnp.uint32)
    flag = jnp.asarray(rng.integers(0, 2, n, dtype=np.uint32))

    # sorts
    def s3(c):
        a, b, d = lax.sort((hi ^ c, lo, idx), num_keys=2, is_stable=True)
        return c + a[0]

    fused_time(f"sort {n} 3-lane (2 keys)", s3, jnp.uint32(1))

    def s4(c):
        a, b, d, e = lax.sort((flag ^ (c & 1), hi, lo, idx), num_keys=3,
                              is_stable=True)
        return c + a[0]

    fused_time(f"sort {n} 4-lane (3 keys)", s4, jnp.uint32(1))

    def s1p3(c):
        a, b, d, e = lax.sort((flag ^ (c & 1), hi, lo, idx), num_keys=1,
                              is_stable=True)
        return c + a[0]

    fused_time(f"sort {n} 4-lane (1 key, stable)", s1p3, jnp.uint32(1))

    # gathers from a big table
    table2 = jnp.zeros((cap, 2), jnp.uint32)
    slots = jnp.asarray(rng.integers(0, cap, R, dtype=np.int32))

    def g_row(c):
        t, x = c
        r = t[(slots + x) & (cap - 1)]
        return (t, x + r[0, 0].astype(jnp.int32) + 1)

    fused_time(f"gather {R} rows [2]u32 of 2^26-row table", g_row,
               (table2, jnp.int32(0)))

    tb8 = jnp.zeros((cap // 8, 8, 2), jnp.uint32)

    def g_b8(c):
        t, x = c
        r = t[(slots + x) & (cap // 8 - 1)]
        return (t, x + r[0, 0, 0].astype(jnp.int32) + 1)

    fused_time(f"gather {R} buckets [8,2]u32", g_b8, (tb8, jnp.int32(0)))

    tb16 = jnp.zeros((cap // 16, 16, 2), jnp.uint32)

    def g_b16(c):
        t, x = c
        r = t[(slots + x) & (cap // 16 - 1)]
        return (t, x + r[0, 0, 0].astype(jnp.int32) + 1)

    fused_time(f"gather {R} buckets [16,2]u32", g_b16, (tb16, jnp.int32(0)))

    # scatters
    rows2 = jnp.asarray(rng.integers(0, 1 << 32, (R, 2), dtype=np.uint32))

    def sc_row(c):
        t, x = c
        t = t.at[(slots + x) & (cap - 1)].set(rows2, mode="drop")
        return (t, x + 1)

    fused_time(f"scatter {R} rows [2]u32 into 2^26-row", sc_row,
               (table2, jnp.int32(0)))

    rows7 = jnp.asarray(rng.integers(0, 1 << 32, (R, 7), dtype=np.uint32))
    q7 = jnp.zeros((1 << 21, 7), jnp.uint32)

    def sc_q7(c):
        t, x = c
        t = t.at[(slots + x) & ((1 << 21) - 1)].set(rows7, mode="drop")
        return (t, x + 1)

    fused_time(f"scatter {R} rows [7]u32 into 2^21-row queue", sc_q7,
               (q7, jnp.int32(0)))

    rows34 = jnp.asarray(rng.integers(0, 1 << 31, (R, 34), dtype=np.int32))
    q34 = jnp.zeros((1 << 21, 34), jnp.int32)

    def sc_q34(c):
        t, x = c
        t = t.at[(slots + x) & ((1 << 21) - 1)].set(rows34, mode="drop")
        return (t, x + 1)

    fused_time(f"scatter {R} rows [34]i32 into 2^21-row queue", sc_q34,
               (q34, jnp.int32(0)))

    def g_q7(c):
        t, x = c
        r = t[(slots + x) & ((1 << 21) - 1)]
        return (t, x + r[0, 0].astype(jnp.int32) + 1)

    fused_time(f"gather {R} rows [7]u32 from 2^21-row queue", g_q7,
               (q7, jnp.int32(0)))

    # monotonic (compaction-style) scatter: targets sorted ascending
    mono = jnp.sort(slots) % (1 << 21)

    def sc_mono(c):
        t, x = c
        t = t.at[jnp.minimum(mono + x, (1 << 21) - 1)].set(rows7, mode="drop")
        return (t, x + 1)

    fused_time(f"scatter {R} rows [7]u32 monotonic tgts", sc_mono,
               (q7, jnp.int32(0)))

    # dynamic_slice-based contiguous write (append simulation)
    def ds_app(c):
        t, x = c
        t = lax.dynamic_update_slice(t, rows7, (x & ((1 << 20)), 0))
        return (t, x + 1)

    fused_time(f"dyn_update_slice {R}x7 contiguous append", ds_app,
               (q7, jnp.int32(0)))

    # MXU parity fingerprint: bits [n, 224] x basis_bits [224, 64]
    nbits = 224
    bits = jnp.asarray(rng.integers(0, 2, (n, nbits), dtype=np.int8))
    basis = jnp.asarray(rng.integers(0, 2, (nbits, 64), dtype=np.int8))

    def mxu_fp(c):
        b = (bits ^ (c & 1)).astype(jnp.bfloat16)
        acc = jax.lax.dot_general(b, basis.astype(jnp.bfloat16), (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        par = acc.astype(jnp.int32) & 1
        w = jnp.arange(32, dtype=jnp.uint32)
        lo32 = (par[:, :32].astype(jnp.uint32) << w).sum(axis=1)
        hi32 = (par[:, 32:].astype(jnp.uint32) << w).sum(axis=1)
        return c + lo32[0] + hi32[0]

    fused_time(f"MXU parity fp {n}x{nbits}->64", mxu_fp, jnp.uint32(1))

    # current XOR-tree fp for comparison
    basis32 = jnp.asarray(rng.integers(0, 1 << 32, (nbits,), dtype=np.uint32))

    def xor_fp(c):
        mask = (bits ^ (c & 1)).astype(jnp.uint32)
        x = mask * basis32
        m = x.shape[-1]
        while m > 1:
            half = m // 2
            x = x[..., :half] ^ x[..., half:2 * half] if m % 2 == 0 else jnp.concatenate(
                [x[..., :half] ^ x[..., half:2 * half], x[..., 2 * half:]], axis=-1)
            m = x.shape[-1]
        return c + x[0, 0]

    fused_time(f"XOR-tree fp {n}x{nbits}->32 (one half)", xor_fp, jnp.uint32(1))

    # scatter-add counters (current) vs compare-reduce
    act = jnp.asarray(rng.integers(0, 30, n, dtype=np.int32))
    cnt = jnp.zeros(31, jnp.uint32)

    def sc_add(c):
        t, x = c
        t = t.at[jnp.minimum(act + (x & 1), 30)].add(1)
        return (t, x + 1)

    fused_time(f"scatter-add {n} into 31 bins", sc_add, (cnt, jnp.int32(0)))

    def cmp_red(c):
        t, x = c
        oh = (act[:, None] == jnp.arange(31)[None, :] - (x & 1)).astype(jnp.uint32)
        return (t + oh.sum(0), x + 1)

    fused_time(f"compare-reduce {n} into 31 bins", cmp_red, (cnt, jnp.int32(0)))


def bench_bucket_row_layout():
    """[nb, 16] u32 interleaved bucket rows (lo0,hi0,...,lo7,hi7) vs the
    materialized reshape of a flat [cap, 2] table."""
    import numpy as np
    rng = np.random.default_rng(0)
    cap = 1 << 26
    nb = cap // 8
    R = 262144
    bid = jnp.asarray(rng.integers(0, nb, R, dtype=np.int32))
    t16 = jnp.zeros((nb, 16), jnp.uint32)
    t2 = jnp.zeros((cap, 2), jnp.uint32)

    def g16(c):
        t, x = c
        r = t[(bid + x) & (nb - 1)]
        return (t, x + r[0, 0].astype(jnp.int32) + 1)

    fused_time(f"gather {R} rows [16]u32 of [nb,16]", g16, (t16, jnp.int32(0)))

    def g_reshape(c):
        t, x = c
        r = t.reshape(nb, 8, 2)[(bid + x) & (nb - 1)]
        return (t, x + r[0, 0, 0].astype(jnp.int32) + 1)

    fused_time(f"gather {R} via reshape of flat [cap,2]", g_reshape,
               (t2, jnp.int32(0)))

    # claim scatter: two element scatters (lo col, hi col) into [nb, 16]
    C = 262144
    cb = jnp.asarray(rng.integers(0, nb, C, dtype=np.int32))
    cs = jnp.asarray(rng.integers(0, 8, C, dtype=np.int32))
    vlo = jnp.asarray(rng.integers(0, 1 << 32, C, dtype=np.uint32))
    vhi = jnp.asarray(rng.integers(0, 1 << 32, C, dtype=np.uint32))

    def sc16(c):
        t, x = c
        b = (cb + x) & (nb - 1)
        t = t.at[b, 2 * cs].set(vlo)
        t = t.at[b, 2 * cs + 1].set(vhi)
        return (t, x + 1)

    fused_time(f"2x element scatter {C} into [nb,16]", sc16, (t16, jnp.int32(0)))

    rows2 = jnp.stack([vlo, vhi], 1)

    def sc2(c):
        t, x = c
        t = t.at[((cb + x) & (nb - 1)) * 8 + cs].set(rows2)
        return (t, x + 1)

    fused_time(f"row scatter {C} into flat [cap,2]", sc2, (t2, jnp.int32(0)))


def bench_windowed_scatter():
    """lax.scatter of [C,2] windows into [nb,16] at (b, 2s) vs 2x element."""
    rng = np.random.default_rng(0)
    nb = (1 << 26) // 8
    C = 131072
    cb = jnp.asarray(rng.integers(0, nb, C, dtype=np.int32))
    cs = jnp.asarray(rng.integers(0, 8, C, dtype=np.int32))
    vlo = jnp.asarray(rng.integers(0, 1 << 32, C, dtype=np.uint32))
    vhi = jnp.asarray(rng.integers(0, 1 << 32, C, dtype=np.uint32))
    t16 = jnp.zeros((nb, 16), jnp.uint32)
    rows = jnp.stack([vlo, vhi], 1)  # [C, 2]
    dn = lax.ScatterDimensionNumbers(
        update_window_dims=(1,), inserted_window_dims=(0,),
        scatter_dims_to_operand_dims=(0, 1))

    def scw(c):
        t, x = c
        idx = jnp.stack([(cb + x) & (nb - 1), 2 * cs], 1)  # [C, 2]
        t = lax.scatter(t, idx, rows, dn,
                        mode=lax.GatherScatterMode.FILL_OR_DROP)
        return (t, x + 1)

    fused_time(f"windowed scatter {C}x[2] into [nb,16]", scw, (t16, jnp.int32(0)))

    def sc2e(c):
        t, x = c
        b = (cb + x) & (nb - 1)
        t = t.at[b, 2 * cs].set(vlo)
        t = t.at[b, 2 * cs + 1].set(vhi)
        return (t, x + 1)

    fused_time(f"2x element scatter {C} into [nb,16]", sc2e, (t16, jnp.int32(0)))


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("bench", nargs="?", default="main",
                    choices=["main", "bucket-layout", "wscatter"])
    which = ap.parse_args().bench
    {"main": main, "bucket-layout": bench_bucket_row_layout,
     "wscatter": bench_windowed_scatter}[which]()
