#!/usr/bin/env python
"""Engine-free lint gate over the specs tree (CI entry point).

    python tools/lintgate.py [SPECS_DIR]

Runs speclint + the certified abstract interpretation over every
MC.cfg under SPECS_DIR (default: the repo's specs/), printing one line
per spec plus its findings, and exits nonzero on any error-severity
finding.  Milliseconds per spec - no jax import, no engine build - so
it belongs in front of every commit touching specs/.  The same pass
runs as ``python -m jaxtlc.analysis --gate`` and as a tier-1 test
(tests/test_absint.py), so the committed tree can never drift into an
error-class lint silently.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


# every engine factory CI expects audited (mirrors the tier-1 pin in
# tests/test_analysis.py::test_selfcheck_registry_pinned); importing
# the registry is jax-free, so this stays an engine-free gate
REQUIRED_FACTORIES = (
    "covered", "covsharded", "deferred", "enumerator", "fused",
    "infer", "narrowed", "phased", "pipelined", "por", "sharded",
    "shardspill", "sim", "sortfree", "spill", "struct", "sweep",
    "symmetry",
)


def check_factories() -> int:
    """Engine-free registry pin: every REQUIRED factory (the sort-free
    commit engine, ISSUE 12, and the deferred-evaluation engine,
    ISSUE 15, included) must be registered for the
    `python -m jaxtlc.analysis --self-check` audit - a commit that
    drops one fails here before any engine builds."""
    from jaxtlc.analysis.selfcheck import FACTORIES

    missing = sorted(set(REQUIRED_FACTORIES) - set(FACTORIES))
    if missing:
        print(f"lintgate: selfcheck registry is missing {missing} - "
              "the factory would ship unaudited", file=sys.stderr)
        return 1
    print(f"lintgate: selfcheck registry covers "
          f"{len(REQUIRED_FACTORIES)} factories"
          " (run `python -m jaxtlc.analysis --self-check --tiny` for "
          "the full audit)")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "specs",
    )
    from jaxtlc.analysis.gate import run_gate

    rc = run_gate(root)
    return rc or check_factories()


if __name__ == "__main__":
    sys.exit(main())
