#!/usr/bin/env python
"""Engine-free lint gate over the specs tree (CI entry point).

    python tools/lintgate.py [SPECS_DIR]

Runs speclint + the certified abstract interpretation over every
MC.cfg under SPECS_DIR (default: the repo's specs/), printing one line
per spec plus its findings, and exits nonzero on any error-severity
finding.  Milliseconds per spec - no jax import, no engine build - so
it belongs in front of every commit touching specs/.  The same pass
runs as ``python -m jaxtlc.analysis --gate`` and as a tier-1 test
(tests/test_absint.py), so the committed tree can never drift into an
error-class lint silently.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "specs",
    )
    from jaxtlc.analysis.gate import run_gate

    return run_gate(root)


if __name__ == "__main__":
    sys.exit(main())
