"""Per-phase cost microbench at the REAL scaled-run shapes (chunk 128k,
L=12, ncand 1.57M, R=C=A=256k, fp table 2^26).  Complements microbench.py
(which measured primitive costs at smaller shapes) - this one prices the
exact step_body phases so optimization targets the measured sink, not a
guessed one."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

K = 16


def fused_time(name, body, carry, reps=3):
    @jax.jit
    def loop(c):
        return lax.fori_loop(0, K, lambda _, cc: body(cc), c)

    out = jax.block_until_ready(loop(carry))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(loop(carry))
        best = min(best, time.perf_counter() - t0)
    print(f"{name:52s} {best / K * 1e3:9.3f} ms", flush=True)
    return out


def main():
    rng = np.random.default_rng(0)
    chunk = 131072
    L = 12
    n = chunk * L
    R = 2 * chunk
    cap = 1 << 26
    nb = cap // 8
    print(f"dev={jax.devices()[0]} chunk={chunk} ncand={n} R={R}", flush=True)

    lo = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    hi = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    idx = jnp.arange(n, dtype=jnp.uint32)
    flag = jnp.asarray(rng.integers(0, 4, n, dtype=np.uint32) == 0)  # ~25% valid

    # sort1 as committed: 4 arrays, 3 keys, stable
    def s4k3(c):
        a, b, d, e = lax.sort(((~flag).astype(jnp.uint32), hi, lo ^ c, idx),
                              num_keys=3, is_stable=True)
        return c + a[0]

    fused_time(f"sort1 now: {n} 4-arr 3-key stable", s4k3, jnp.uint32(1))

    # sort1 alt: invalid encoded as fp (0,0) -> 3 arrays, 2 keys
    lo0 = jnp.where(flag, lo, 0)
    hi0 = jnp.where(flag, hi, 0)

    def s3k2(c):
        a, b, d = lax.sort((hi0, lo0 ^ (c & jnp.uint32(0)) ^ lo0 * 0 + (lo0 ^ c * 0), idx),
                           num_keys=2, is_stable=True)
        return c + a[0]

    def s3k2b(c):
        a, b, d = lax.sort((hi0 ^ (c * 0), lo0, idx), num_keys=2,
                           is_stable=True)
        return c + a[0]

    fused_time(f"sort1 alt: {n} 3-arr 2-key stable", s3k2b, jnp.uint32(1))

    # sort2 as committed: 4 arrays, 1 key, stable
    def s4k1(c):
        a, b, d, e = lax.sort((flag.astype(jnp.uint32) ^ (c * 0), lo, hi, idx),
                              num_keys=1, is_stable=True)
        return c + b[0]

    fused_time(f"sort2 now: {n} 4-arr 1-key stable", s4k1, jnp.uint32(1))

    # enqueue sort as committed: full-n 2-arr 2-key
    def enq_full(c):
        a, b = lax.sort((flag.astype(jnp.uint32) ^ (c * 0), idx), num_keys=2,
                        is_stable=True)
        return c + b[0]

    fused_time(f"enq sort now: {n} 2-arr 2-key", enq_full, jnp.uint32(1))

    def enq_R(c):
        a, b = lax.sort((flag[:R].astype(jnp.uint32) ^ (c * 0), idx[:R]),
                        num_keys=2, is_stable=True)
        return c + b[0]

    fused_time(f"enq sort alt: {R} 2-arr 2-key", enq_R, jnp.uint32(1))

    # probe gather at R of [nb,16]
    t16 = jnp.zeros((nb, 16), jnp.uint32)
    bid = jnp.asarray(rng.integers(0, nb, R, dtype=np.int32))

    def g16(c):
        t, x = c
        r = t[(bid + x) & (nb - 1)]
        return (t, x + r[0, 0].astype(jnp.int32) + 1)

    fused_time(f"probe gather {R} rows [16]u32", g16, (t16, jnp.int32(0)))

    # claim scatter now: 2x element scatter width R
    cb = jnp.asarray(rng.integers(0, nb, R, dtype=np.int32))
    cs = jnp.asarray(rng.integers(0, 8, R, dtype=np.int32))
    vlo = jnp.asarray(rng.integers(0, 1 << 32, R, dtype=np.uint32))
    vhi = jnp.asarray(rng.integers(0, 1 << 32, R, dtype=np.uint32))

    def sc2e(c):
        t, x = c
        b = (cb + x) & (nb - 1)
        t = t.at[b, 2 * cs].set(vlo, mode="drop")
        t = t.at[b, 2 * cs + 1].set(vhi, mode="drop")
        return (t, x + 1)

    fused_time(f"claim now: 2x elem scatter {R}", sc2e, (t16, jnp.int32(0)))

    C2 = chunk

    def sc2e_h(c):
        t, x = c
        b = (cb[:C2] + x) & (nb - 1)
        t = t.at[b, 2 * cs[:C2]].set(vlo[:C2], mode="drop")
        t = t.at[b, 2 * cs[:C2] + 1].set(vhi[:C2], mode="drop")
        return (t, x + 1)

    fused_time(f"claim alt: 2x elem scatter {C2}", sc2e_h, (t16, jnp.int32(0)))

    # stats now: scatter-add A into chunk+1 bins + A into 31 bins
    A = R
    srcrow = jnp.asarray(rng.integers(0, chunk, A, dtype=np.int32))
    acts = jnp.asarray(rng.integers(0, 30, A, dtype=np.int32))
    deg = jnp.zeros(chunk + 1, jnp.uint32)
    cnt = jnp.zeros(31, jnp.uint32)

    def deg_sc(c):
        t, x = c
        t = t.at[jnp.minimum(srcrow + (x & 1), chunk)].add(1)
        return (t, x + 1)

    fused_time(f"deg scatter-add {A} into {chunk+1} bins", deg_sc,
               (deg, jnp.int32(0)))

    def act_sc(c):
        t, x = c
        t = t.at[jnp.minimum(acts + (x & 1), 30)].add(1)
        return (t, x + 1)

    fused_time(f"act scatter-add {A} into 31 bins", act_sc,
               (cnt, jnp.int32(0)))

    def act_cr(c):
        t, x = c
        oh = (acts[:, None] == (jnp.arange(31)[None, :] - (x & 1)))
        return (t + oh.sum(0).astype(jnp.uint32), x + 1)

    fused_time(f"act compare-reduce {A} into 31 bins", act_cr,
               (cnt, jnp.int32(0)))

    # deg alt: sorted-run lengths -> [L+2] hist (srcrow sorted ascending)
    ssrc = jnp.sort(srcrow)

    def deg_runs(c):
        t, x = c
        s = ssrc + (x & 1)
        startf = jnp.concatenate([jnp.ones(1, bool), s[1:] != s[:-1]])
        pos = jnp.arange(A, dtype=jnp.int32)
        run0 = lax.cummax(jnp.where(startf, pos, 0))
        endf = jnp.concatenate([s[1:] != s[:-1], jnp.ones(1, bool)])
        ln = jnp.where(endf, pos - run0 + 1, 0)
        lnc = jnp.minimum(ln, L + 1)
        oh = (lnc[:, None] == (jnp.arange(1, L + 2)[None, :]))
        hist = oh.sum(0).astype(jnp.uint32)
        return (t.at[: L + 1].add(hist), x + 1)

    fused_time(f"deg run-length {A} -> [L+2] hist", deg_runs,
               (jnp.zeros(L + 2, jnp.uint32), jnp.int32(0)))

    # enqueue row gather A of [n,7] + contiguous write
    packed = jnp.asarray(rng.integers(0, 1 << 32, (n, 7), dtype=np.uint32))
    q = jnp.zeros((1 << 21, 7), jnp.uint32)
    gidx = jnp.asarray(rng.integers(0, n, A, dtype=np.int32))

    def enq_g(c):
        q_, x = c
        rows = packed[(gidx + x) % n]
        q_ = lax.dynamic_update_slice(q_, rows, (jnp.int32(0), jnp.int32(0)))
        return (q_, x + 1)

    fused_time(f"enq gather {A} rows [7]u32 + contig write", enq_g,
               (q, jnp.int32(0)))


if __name__ == "__main__":
    main()
