#!/bin/bash
# Watch for the axon relay (127.0.0.1:8083) to come back; append one
# timestamp per down->up TRANSITION so a consumer sees each comeback
# exactly once.  The relay is a launcher-side stdio pump (see memory:
# axon-relay-jax-cpu-pattern); it cannot be restarted from inside the
# container, only observed.
MARKER=/tmp/tpu_back.marker
up=0
while true; do
  if timeout 3 bash -c '</dev/tcp/127.0.0.1/8083' 2>/dev/null; then
    if [ "$up" = 0 ]; then
      date -u +%FT%TZ >> "$MARKER"
      up=1
    fi
  else
    up=0
  fi
  sleep 60
done
