"""Phase profiler for the v4 engine loop on the scaled workload.

Unlike tools/profile_scaled.py (whose host-side random-walk setup is
unusably slow at 128k chunks), this drives the REAL engine to a mid-run
carry (realistic frontier block + realistic table load), then times each
phase of the engine step in a fused ``lax.fori_loop`` so the tunneled
dispatch floor (~64 ms) is amortized and subtracted.

Round 7 additions: per-stage wall attribution for the pipelined engine
(expand stage measured directly through the backend seam, commit stage
by subtraction from the real fused step) and an overlap-efficiency line
(wall saved by the pipelined step over min(expand, commit), the
theoretical two-stage overlap ceiling).

Usage: python tools/profile_v4.py [--chunk N] [--fpcap LOG2] [--steps K]
       python tools/profile_v4.py --tiny   # FF corner smoke (tier-1)
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

from jaxtlc.config import scaled_config
from jaxtlc.engine.bfs import make_engine
from jaxtlc.engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words_mxu
from jaxtlc.engine.fpset import fpset_insert_sorted
from jaxtlc.spec.codec import get_codec
from jaxtlc.spec.invariants import make_invariant_kernel
from jaxtlc.spec.kernel import make_kernel

K = 16


def fused_time(name, body, carry, floor_s=0.0, reps=3):
    @jax.jit
    def loop(c):
        return lax.fori_loop(0, K, lambda _, cc: body(cc), c)

    out = jax.block_until_ready(loop(carry))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(loop(carry))
        best = min(best, time.perf_counter() - t0)
    per = (best - floor_s) / K
    if name:
        print(f"{name:40s} {per * 1e3:9.3f} ms/iter")
    return out, per


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunk", type=int, default=131072)
    ap.add_argument("--fpcap", type=int, default=26)
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--tiny", action="store_true",
                    help="FF-corner smoke sizing (chunk 256, fp 2^15, "
                         "8 warm steps) so the tier-1 suite can run the "
                         "whole profiler without a TPU")
    args = ap.parse_args(argv)

    if args.tiny:
        from jaxtlc.config import ModelConfig

        cfg = ModelConfig(False, False)
        if args.chunk == 131072:
            args.chunk = 256
        if args.fpcap == 26:
            args.fpcap = 15
        if args.steps == 60:
            args.steps = 8
        qcap = 1 << 13
    else:
        cfg, _ = scaled_config()
        qcap = 1 << 21
    cdc = get_codec(cfg)
    F = cdc.n_fields
    W = (cdc.nbits + 31) // 32
    step = make_kernel(cfg)
    L = step.n_lanes
    inv_check = make_invariant_kernel(cfg)
    chunk = args.chunk
    ncand = chunk * L
    print(f"chunk={chunk} L={L} F={F} W={W} nbits={cdc.nbits} "
          f"ncand={ncand} dev={jax.devices()[0]}")

    # drive the real engine to a mid-run carry (donate=False: the same
    # warmed carry seeds every timing closure below, repeatedly)
    init_fn, _, step_fn = make_engine(
        cfg, chunk=chunk, queue_capacity=qcap,
        fp_capacity=1 << args.fpcap, donate=False,
    )
    carry = init_fn()
    t0 = time.time()
    for _ in range(args.steps):
        carry = step_fn(carry)
    carry = jax.block_until_ready(carry)
    print(f"  warmed {args.steps} steps in {time.time() - t0:.1f}s: "
          f"distinct={int(carry.distinct)} level={int(carry.level)} "
          f"level_n={int(carry.level_n)} qhead={int(carry.qhead)}")

    block = lax.dynamic_slice(
        carry.queue, (carry.parity, jnp.int32(0), jnp.int32(0)),
        (1, chunk, W))[0]
    batch = cdc.unpack(block)
    fps = carry.fps

    _, floor_per = fused_time("", lambda c: c + 1, jnp.int32(0))
    floor_s = floor_per * K
    print(f"{'dispatch floor (whole fused loop)':40s} {floor_s * 1e3:9.3f} ms")

    # 0. whole step body, for reference
    body_full = None  # step_fn is cond-wrapped; time via engine below

    # 1. unpack
    def b_unpack(c):
        b = cdc.unpack(block ^ c[None, :])
        return c ^ b[0, :1].astype(jnp.uint32)

    _, t_unpack = fused_time("unpack", b_unpack,
                             jnp.zeros(W, jnp.uint32), floor_s)

    # 2. kernel expansion
    def b_kernel(c):
        s, v, a, af, ov = jax.vmap(step)(c)
        return c ^ s[:, 0, :1]

    _, t_kernel = fused_time("vmap(step) expansion", b_kernel, batch, floor_s)

    succs, valid, action, afail, ovf = jax.vmap(step)(batch)
    flat = succs.reshape(ncand, F)
    fvalid = valid.reshape(-1)
    print(f"  valid: {int(fvalid.sum())}/{ncand}")

    # 3. invariants
    def b_inv(c):
        inv = jax.vmap(inv_check)(c)
        return c ^ inv[:, None].astype(jnp.int32)

    _, t_inv = fused_time("invariant kernel", b_inv, flat, floor_s)

    # 4. pack
    def b_pack(c):
        p = cdc.pack(c)
        return c ^ p[:, :1].astype(jnp.int32)

    _, t_pack = fused_time("pack", b_pack, flat, floor_s)

    packed = cdc.pack(flat)

    # 5. fingerprint (MXU)
    def b_fp(c):
        lo, hi = fp64_words_mxu(c, cdc.nbits, DEFAULT_FP_INDEX, DEFAULT_SEED)
        return c ^ lo[:, None]

    _, t_fp = fused_time("fp64 fingerprint (MXU)", b_fp, packed, floor_s)

    lo, hi = fp64_words_mxu(packed, cdc.nbits, DEFAULT_FP_INDEX, DEFAULT_SEED)
    R = min(2 * chunk, ncand)

    # 6. fpset_insert_sorted at real load (vary lo so probes are honest;
    # table occupancy grows negligibly over K reps)
    def b_ins(c):
        fps_c, x = c
        f2, is_new_c, c_idx, nreps = fpset_insert_sorted(
            fps_c, lo ^ x, hi, fvalid, probe_width=R, claim_width=R)
        return (f2, x + jnp.uint32(1))

    _, t_ins = fused_time("fpset_insert_sorted (2 sorts + probe)", b_ins,
                          (fps, jnp.uint32(1)), floor_s)

    # 6a. sort 1 alone (group duplicates): 4 arrays, 3 keys
    idx = jnp.arange(ncand, dtype=jnp.uint32)

    def b_sort1(c):
        inval = (~fvalid).astype(jnp.uint32)
        s_inv, s_hi, s_lo, s_idx = lax.sort(
            (inval, hi, lo ^ c, idx), num_keys=3, is_stable=True)
        return c + s_lo[0]

    _, t_sort1 = fused_time("  sort1 (4 arrays, 3 keys)", b_sort1,
                            jnp.uint32(1), floor_s)

    # 6b. sort 2 alone (compact reps): 4 arrays, 1 key
    rep = fvalid

    def b_sort2(c):
        nonrep = (~rep).astype(jnp.uint32)
        _, c_lo, c_hi, c_idx = lax.sort(
            (nonrep, lo ^ c, hi, idx), num_keys=1, is_stable=True)
        return c + c_lo[0]

    _, t_sort2 = fused_time("  sort2 (4 arrays, 1 key)", b_sort2,
                            jnp.uint32(1), floor_s)

    # 6c. probe block alone at R rows
    from jaxtlc.engine.fpset import _probe_block, _mix, _remap
    mlo, mhi = _mix(lo[:R], hi[:R])
    mlo, mhi = _remap(mlo, mhi)
    s_hi2, s_lo2 = lax.sort((mhi, mlo), num_keys=2)

    def b_probe(c):
        tbl, x = c
        t2, isn = _probe_block(tbl, s_lo2 ^ x, s_hi2, fvalid[:R], R)
        return (t2, x + jnp.uint32(1))

    _, t_probe = fused_time("  probe block (R rows)", b_probe,
                            (fps.table, jnp.uint32(1)), floor_s)

    # 7. enqueue sort + gather + contiguous write
    A = min(2 * chunk, ncand)
    is_new_c = fvalid  # worst-ish case

    def b_enq(c):
        q, x = c
        _, e_idx = lax.sort(
            ((~is_new_c).astype(jnp.uint32), (idx + x)), num_keys=2,
            is_stable=True)
        rows_a = packed[e_idx[:A].astype(jnp.int32)]
        q = lax.dynamic_update_slice(q, rows_a[None], (0, 0, jnp.int32(0)))
        return (q, x + jnp.uint32(1))

    _, t_enq = fused_time("enqueue (sort + A-gather + write)", b_enq,
                          (carry.queue, jnp.uint32(1)), floor_s)

    # 8. per-action stats
    from jaxtlc.spec.labels import LABELS
    from jaxtlc.spec.kernel import lane_layout
    CL, _ = lane_layout(cfg)
    nc = cdc.nc
    n_labels = len(LABELS)
    pc_off = cdc.offsets["pc"]
    label_ids = jnp.arange(n_labels, dtype=jnp.int32)

    def b_stats(c):
        gen_counts = jnp.zeros(n_labels, jnp.uint32)
        for ci in range(nc):
            vc = valid[:, ci * CL:(ci + 1) * CL].sum(axis=1)
            pcs = batch[:, pc_off + ci] + c
            gen_counts = gen_counts + (
                (pcs[:, None] == label_ids[None, :]) * vc[:, None]
            ).sum(axis=0).astype(jnp.uint32)
        return c + gen_counts[0].astype(jnp.int32)

    _, t_stats = fused_time("per-action gen counters", b_stats,
                            jnp.int32(0), floor_s)

    total = (t_unpack + t_kernel + t_inv + t_pack + t_fp + t_ins + t_enq
             + t_stats)
    print(f"{'SUM of phases':40s} {total * 1e3:9.3f} ms/iter")
    print(f"  -> at ~{chunk} distinct/iter ceiling: "
          f"{chunk / total / 1e3:.0f}k distinct/s")

    # whole real step via the engine's own jitted step_fn (one dispatch
    # per step; subtract the measured dispatch floor per call)
    out = jax.block_until_ready(step_fn(carry))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        c2 = carry
        for _ in range(K):
            c2 = step_fn(c2)
        jax.block_until_ready(c2)
        best = min(best, time.perf_counter() - t0)
    # each step_fn call is its own dispatch, so subtract the whole
    # dispatch floor per call (floor_s = one fused-loop dispatch's cost)
    per = best / K - floor_s
    print(f"{'REAL step_fn (x16, floor-adjusted)':40s} {per * 1e3:9.3f} ms/iter")

    # --- round 7: expand/commit stage attribution + overlap efficiency ---
    # expand measured directly through the backend seam (the SAME
    # function the pipelined body runs); commit attributed by
    # subtraction from the real fused step so the two columns add up to
    # what the engine actually pays
    from jaxtlc.engine.backend import kubeapi_backend, make_expand_stage

    backend = kubeapi_backend(cfg)
    expand_fn = make_expand_stage(
        backend, chunk, True, DEFAULT_FP_INDEX, DEFAULT_SEED
    )
    mask_all = jnp.ones(chunk, bool)

    def b_expand(c):
        ex = expand_fn(c, mask_all)
        return c ^ ex.lo[:chunk, None].astype(jnp.int32)

    _, t_expand = fused_time("expand stage (seam)", b_expand, batch,
                             floor_s)
    t_commit = max(per - t_expand, 0.0)
    print(f"{'commit stage (real step - expand)':40s} "
          f"{t_commit * 1e3:9.3f} ms/iter")

    # pipelined engine at the same geometry, warmed identically: the
    # per-step delta over the fused engine is the realized overlap;
    # min(expand, commit) is the two-stage ceiling
    pinit, _, pstep = make_engine(
        cfg, chunk=chunk, queue_capacity=qcap,
        fp_capacity=1 << args.fpcap, pipeline=True, donate=False,
    )
    pcarry = pinit()
    for _ in range(args.steps):
        pcarry = pstep(pcarry)
    pcarry = jax.block_until_ready(pcarry)
    jax.block_until_ready(pstep(pcarry))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        c2 = pcarry
        for _ in range(K):
            c2 = pstep(c2)
        jax.block_until_ready(c2)
        best = min(best, time.perf_counter() - t0)
    per_pipe = best / K - floor_s
    print(f"{'PIPELINED step_fn (x16, floor-adjusted)':40s} "
          f"{per_pipe * 1e3:9.3f} ms/iter")
    ceiling = min(t_expand, t_commit)
    saved = per - per_pipe
    eff = saved / ceiling if ceiling > 0 else 0.0
    print(f"overlap efficiency: {eff:6.2f} "
          f"(saved {saved * 1e3:.3f} ms of {ceiling * 1e3:.3f} ms "
          f"overlappable per step)")


if __name__ == "__main__":
    main()
