"""Scaled-workload pin validation (VERDICT r3 item 5: de-circularize).

Re-derives the scaled-config expected counts by running INDEPENDENT
engine configurations and recording their agreement in
SCALED_VALIDATION.json - the artifact bench.py's EXPECT pins and
tests/test_scaled.py cite.  Independence axes:

* engine geometry: different chunk sizes and fingerprint-table
  capacities execute different instruction schedules, candidate
  groupings and probe patterns - identical counts across them rule out
  geometry-dependent dedup/enqueue bugs;
* platform: the TPU path (MXU fingerprints, real HBM layouts) vs the
  forced-CPU path (totally different XLA backend lowering);
* engine variant: the hybrid (host-tier dedup) engine shares no
  fingerprint-set or queue code with the device engine.

Usage:
    python tools/validate_scaled.py [--workload 2x1|1x2] [--quick]
        [--engine device|hybrid] [--chunk N] [--fpcap LOG2]

Each invocation appends one validated run to the artifact (exact-count
agreement with the recorded pins is asserted; a mismatch aborts loudly
WITHOUT touching the file).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ARTIFACT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "SCALED_VALIDATION.json",
)

PINS = {
    "2x1FF": (62014325, 19359985, 186),  # the bench.py --scaled flagship
    "1x2FF": (30582846, 9942722, 160),  # tests/test_scaled.py slow pin
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", choices=["2x1", "1x2"], default="2x1")
    ap.add_argument("--engine", choices=["device", "hybrid"],
                    default="device")
    ap.add_argument("--chunk", type=int, default=16384)
    ap.add_argument("--fpcap", type=int, default=25, help="log2")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU platform")
    args = ap.parse_args()

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from jaxtlc.config import make_scaled

    key = f"{args.workload}FF"
    cfg = (make_scaled(2, 1, False, False) if args.workload == "2x1"
           else make_scaled(1, 2, False, False))
    t0 = time.time()
    if args.engine == "device":
        from jaxtlc.engine.bfs import check

        r = check(cfg, chunk=args.chunk, queue_capacity=1 << 21,
                  fp_capacity=1 << args.fpcap)
    else:
        from jaxtlc.engine.hybrid import check_hybrid

        r = check_hybrid(cfg, chunk=args.chunk)
    got = (r.generated, r.distinct, r.depth)
    print(f"{key} {args.engine} chunk={args.chunk}: {got} "
          f"in {time.time() - t0:.1f}s on {jax.devices()[0]}")
    if got != PINS[key]:
        print(f"MISMATCH: expected {PINS[key]}", file=sys.stderr)
        return 1

    entry = {
        "workload": key,
        "engine": args.engine,
        "platform": str(jax.devices()[0]),
        "chunk": args.chunk,
        "fp_capacity_log2": args.fpcap if args.engine == "device" else None,
        "generated": r.generated,
        "distinct": r.distinct,
        "depth": r.depth,
        "wall_s": round(r.wall_s, 2),
        "date": time.strftime("%Y-%m-%d"),
    }
    doc = {"pins": {k: list(v) for k, v in PINS.items()}, "runs": []}
    if os.path.exists(ARTIFACT):
        with open(ARTIFACT) as f:
            doc = json.load(f)
    doc["runs"].append(entry)
    tmp = ARTIFACT + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, ARTIFACT)
    print(f"recorded in {ARTIFACT} ({len(doc['runs'])} runs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
