"""Device-liveness microbench (ISSUE 1 satellite): one JSON line in the
bench.py style, covering the three phases of the jaxtlc.live pipeline -

    enumerate  - fused append-only distinct-state enumeration
    capture    - edge-relation emission (re-expand + batched id search)
    fixpoint   - tensorized survive-set sweeps for ReconcileCompletes

The metric line reports edges captured per second (the capture pass
dominates at scale and is the subsystem's throughput unit), plus the
fixpoint sweep count and per-phase walls, so perf work attacks the
measured phase instead of a guessed one.

Correctness is a gate, as in bench.py: the fixpoint verdict must be the
known one (ReconcileCompletes is violated in every KubeAPI fault
corner) or the tool reports failure instead of a rate.

Usage:
    python tools/profile_liveness.py                 # FF corner (fast)
    python tools/profile_liveness.py --workload model1
    python tools/profile_liveness.py --workload scaled3x0tt
    python tools/profile_liveness.py --mesh 8        # shard the fixpoint
"""

import argparse
import json
import os
import sys
import time

# sys.path (not PYTHONPATH: the env var breaks the tunneled-TPU plugin
# discovery in this image) so the tool runs from any cwd
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WORKLOADS = {
    # name -> (cfg factory, sizing, pinned distinct states)
    "ff": (lambda: __import__("jaxtlc.config", fromlist=["MATRIX"])
           .MATRIX[(False, False)],
           dict(chunk=256, state_capacity=1 << 14, fp_capacity=1 << 14),
           8203),
    "model1": (lambda: __import__("jaxtlc.config", fromlist=["MODEL_1"])
               .MODEL_1,
               dict(chunk=4096, state_capacity=1 << 18,
                    fp_capacity=1 << 19), 163408),
    "scaled3x0tt": (lambda: __import__(
        "jaxtlc.config", fromlist=["make_scaled"]).make_scaled(3, 0, True,
                                                               True),
        dict(chunk=16384, state_capacity=1 << 24, fp_capacity=1 << 25),
        8869743),
}


def _emit(payload: dict) -> None:
    """The bench.py contract: exactly one JSON line, on every exit path."""
    base = {
        "metric": "liveness_edges_per_s",
        "value": 0,
        "unit": "edges/s",
    }
    base.update(payload)
    print(json.dumps(base), flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="ff", choices=sorted(WORKLOADS))
    ap.add_argument("--mesh", type=int, default=0,
                    help="shard the fixpoint over this many devices")
    args = ap.parse_args()

    try:
        import jax
        import numpy as np

        from jaxtlc.live.capture import capture_edges
        from jaxtlc.live.check import capture_kube_graph
        from jaxtlc.live.fixpoint import has_nonself, surviving_set
        from jaxtlc.spec.codec import get_codec

        cfg_fn, sizing, expect = WORKLOADS[args.workload]
        cfg = cfg_fn()

        t0 = time.time()
        graph = capture_kube_graph(cfg, **sizing)
        capture_wall = time.time() - t0
        if graph.n_states != expect:
            _emit({"error": f"state count {graph.n_states} != pinned "
                            f"{expect}", "workload": args.workload})
            return 1

        mesh = None
        if args.mesh:
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()[: args.mesh]), ("fp",))

        cdc = get_codec(cfg)
        nonself = has_nonself(graph)
        t1 = time.time()
        # ReconcileCompletes zone for reconciler 0: H = {sr[0]}
        fields_off = cdc.offsets["sr"]
        from jaxtlc.live.capture import eval_state_masks

        (in_h,) = eval_state_masks(
            graph, cdc, [lambda f: f[:, fields_off] == 1]
        )
        alive, sweeps = surviving_set(graph, in_h, mesh=mesh,
                                      nonself=nonself)
        fix_wall = time.time() - t1
        if not (in_h & alive).any():
            _emit({"error": "fixpoint verdict flipped (ReconcileCompletes "
                            "is violated in every fault corner)",
                   "workload": args.workload})
            return 1

        wall = time.time() - t0
        _emit({
            "value": round(len(graph.src) / capture_wall, 1),
            "workload": args.workload,
            "states": graph.n_states,
            "edges": int(len(graph.src)),
            "fixpoint_sweeps": int(sweeps),
            "capture_wall_s": round(capture_wall, 3),
            "fixpoint_wall_s": round(fix_wall, 3),
            "wall_s": round(wall, 3),
            "device": str(jax.devices()[0]),
            "mesh": args.mesh or 1,
        })
        return 0
    except Exception as e:  # noqa: BLE001 - the contract is one JSON line
        _emit({"error": f"{type(e).__name__}: {e}"})
        return 1


if __name__ == "__main__":
    sys.exit(main())
