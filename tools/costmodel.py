#!/usr/bin/env python
"""Automated cost-model fitter: the measured baseline for ROADMAP #1.

PERF.md's r4 cost model (the table the MXU commit rewrite will be
judged against) was assembled by hand from profiler scrapes.  This tool
automates it: a chunk-size sweep over the fused and pipelined engines
that, per chunk,

1. drives a short `-phase-timing` run (obs.phases.PhasedRuntime) and
   reads the measured expand/commit walls back FROM the `phase` journal
   events - the same events a live run serves on /events - and
2. carves commit into sort / fpset-probe / enqueue by the differential
   sub-phase profiler (obs.phases.subphase_walls, the profile_v4
   technique as a library),

then fits the PERF-style per-phase linear model ms(chunk) = a + b*chunk
by least squares and writes a committed COSTMODEL.json plus a
PERF.md-ready markdown table.

    python tools/costmodel.py                  # Model_1, committed sweep
    python tools/costmodel.py --chunks 256,512 --out COSTMODEL.json
    python tools/costmodel.py --tiny           # FF smoke (tier-1)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
)

# v2 (ISSUE 12): per-phase fits clamped to nonnegative slopes (the r11
# document carried a nonphysical enqueue slope of -1.32 ms/1k chunk -
# amortized-to-zero measurements fitted through noise), plus the
# sort-free commit columns (ms_per_step_sort_free / fit_sort_free: the
# same sweep measured with the hash-slab dedup) so the before/after of
# the ROADMAP #1 commit rewrite lives in one committed document.
#
# v3 (ISSUE 15): the v2 `inv_fp` wall splits into separate `inv` and
# `fp` columns (the fit could not see which half dominated - it was
# the invariant sweep), a deferred-evaluation sweep rides the same
# document (ms_per_step_deferred / fit_deferred: sort-free commit +
# the commit-site claimant checker), and NEGATIVE INTERCEPTS are
# clamped the way v2 clamped negative slopes (the v2 document carried
# sort a_ms = -0.4441 - a step cannot have negative fixed cost).
COSTMODEL_VERSION = 3

# the phase columns of the emitted table, in pipeline order
PHASES = ("kernel", "inv", "fp", "expand", "sort", "probe", "enqueue",
          "commit", "step")


def _phase_event_walls(backend, chunk: int, qcap: int, fpcap: int,
                       steps: int) -> dict:
    """Measured expand/commit ms/step from `phase` JOURNAL EVENTS of a
    short PhasedRuntime run - the fitter consumes the same event stream
    a live `-phase-timing` run journals and serves."""
    from jaxtlc.obs.journal import RunJournal
    from jaxtlc.obs.phases import PhasedRuntime

    rt = PhasedRuntime(backend, chunk, qcap, fpcap)
    seg = rt.segment_fn(steps)
    carry = rt.init_fn()
    carry = seg(carry)  # warm + compile inside the fenced loop
    rt.recorder.reset()
    carry = seg(carry)
    journal = RunJournal()  # in-memory, schema-validated
    for row in rt.recorder.drain():
        journal.event("phase", **row)
    walls = {"expand": 0.0, "commit": 0.0}
    bodies = 0
    for ev in journal.events:
        walls[ev["phase"]] += ev["wall_s"]
        if ev["phase"] == "expand":
            bodies += ev["bodies"]
    bodies = max(bodies, 1)
    return {
        "expand_ms": 1e3 * walls["expand"] / bodies,
        "commit_ms": 1e3 * walls["commit"] / bodies,
        "bodies": bodies,
    }


def _pipelined_step_ms(backend, chunk: int, qcap: int, fpcap: int,
                       warm: int, K: int, reps: int) -> float:
    """Best-of-`reps` ms/step of the pipelined engine at the same
    geometry, warmed identically (the overlap column of the table)."""
    import jax

    from jaxtlc.engine.bfs import make_backend_engine

    init_fn, _, step_fn = make_backend_engine(
        backend, chunk, qcap, fpcap, pipeline=True, donate=False,
    )
    carry = init_fn()
    for _ in range(warm):
        carry = step_fn(carry)
    carry = jax.block_until_ready(carry)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        c2 = carry
        for _ in range(K):
            c2 = step_fn(c2)
        jax.block_until_ready(c2)
        best = min(best, time.perf_counter() - t0)
    return 1e3 * best / K


def fit_linear(chunks, ms_values) -> dict:
    """Least-squares ms(chunk) = a + b*chunk; b reported per 1k chunk
    (the PERF r4 convention).  Degenerate sweeps (one point) pin the
    intercept to the measurement.

    Slopes are CLAMPED to nonnegative: a wall time cannot shrink as
    the chunk grows, so a negative fitted slope is measurement noise
    through an amortized-to-zero phase (the r11 document's enqueue
    column fitted b = -1.32 ms/1k).  A clamped fit refits at b = 0
    (a = mean) and records `clamped: true`; the table marks it.

    Intercepts are clamped the same way (v3): a phase cannot have
    negative fixed cost, so a negative fitted intercept (the v2
    document's sort a_ms = -0.4441) is noise through a slope-dominated
    phase.  The refit goes through the origin (b = sum(xy)/sum(x^2),
    nonnegative since all measurements are) and records
    `clamped_intercept: true`; the table marks it too."""
    import numpy as np

    x = np.asarray(chunks, float)
    y = np.asarray(ms_values, float)
    if len(x) < 2:
        return {"a_ms": round(float(y[0]), 4), "b_ms_per_1k": 0.0,
                "r2": 1.0}
    b, a = np.polyfit(x, y, 1)
    clamped = b < 0
    clamped_icpt = False
    if clamped:
        b, a = 0.0, float(y.mean())
    elif a < 0:
        clamped_icpt = True
        a = 0.0
        b = float((x * y).sum() / (x * x).sum())
    pred = a + b * x
    ss_res = float(((y - pred) ** 2).sum())
    ss_tot = float(((y - y.mean()) ** 2).sum())
    r2 = 1.0 - ss_res / ss_tot if ss_tot > 0 else 1.0
    out = {"a_ms": round(float(a), 4),
           "b_ms_per_1k": round(float(b) * 1024, 4),
           "r2": round(r2, 4)}
    if clamped:
        out["clamped"] = True
    if clamped_icpt:
        out["clamped_intercept"] = True
    return out


def real_measure(backend, qcap: int, fpcap: int, warm: int, K: int,
                 reps: int, phased_steps: int):
    """measure(chunk) over the real engines: differential sub-phase
    walls (sorted, sort-free, and sort-free + deferred-evaluation
    commit) + phase-event walls + the pipelined step."""
    from jaxtlc.obs.phases import subphase_walls

    def measure(chunk):
        walls = subphase_walls(
            backend, chunk, qcap, fpcap, warm_steps=warm, K=K,
            reps=reps,
        )
        walls_sf = subphase_walls(
            backend, chunk, qcap, fpcap, warm_steps=warm, K=K,
            reps=reps, sort_free=True,
        )
        walls_def = subphase_walls(
            backend, chunk, qcap, fpcap, warm_steps=warm, K=K,
            reps=reps, sort_free=True, deferred=True,
        )
        ev = _phase_event_walls(backend, chunk, qcap, fpcap,
                                phased_steps)
        pipe = _pipelined_step_ms(backend, chunk, qcap, fpcap, warm,
                                  K, reps)
        return walls, ev, pipe, walls_sf, walls_def

    return measure


# deterministic per-phase (a_ms, b_ms_per_chunk) of the synthetic
# measurer: exactly linear, so the tiny smoke can assert the fitter
# RECOVERS them - a real correctness check of the fit path with zero
# engine compiles (tier-1 runs at ~800 s of its 870 s budget; the real
# measurement path is exercised by the committed COSTMODEL.json run)
_SYNTH = {"kernel": (0.5, 0.004), "inv": (0.06, 0.0006),
          "fp": (0.04, 0.0004),
          "expand": (0.6, 0.005), "sort": (0.05, 0.002),
          "probe": (0.1, 0.0015), "enqueue": (0.15, 0.0005),
          "commit": (0.3, 0.004), "step": (0.9, 0.009)}

# the synthetic sort-free walls: the dedup ("sort") column shrinks 4x,
# commit/step shrink by the saving - also exactly linear, so the tiny
# smoke asserts the v2 sort-free fit recovers planted coefficients too
_SYNTH_SF = dict(_SYNTH)
_SYNTH_SF.update({"sort": (0.0125, 0.0005),
                  "commit": (0.2625, 0.0025),
                  "step": (0.8625, 0.0075)})

# the synthetic deferred walls (v3): the inv column shrinks 4x (the
# distinct-first collapse), expand loses that saving, commit absorbs
# the claimant checker - also exactly linear, so the tiny smoke
# asserts the fit_deferred table recovers planted coefficients AND
# the >= 2x inv relation the committed-document test reads off the
# real sweep
_SYNTH_DEF = dict(_SYNTH_SF)
_SYNTH_DEF.update({"inv": (0.015, 0.00015),
                   "expand": (0.555, 0.00455),
                   "commit": (0.2775, 0.002875),
                   "step": (0.8325, 0.007425)})


def synthetic_measure(chunk):
    walls = {p: (a + b * chunk) / 1e3 for p, (a, b) in _SYNTH.items()}
    walls_sf = {p: (a + b * chunk) / 1e3
                for p, (a, b) in _SYNTH_SF.items()}
    walls_def = {p: (a + b * chunk) / 1e3
                 for p, (a, b) in _SYNTH_DEF.items()}
    ev = {"expand_ms": 1e3 * walls["expand"],
          "commit_ms": 1e3 * walls["commit"], "bodies": 8}
    return walls, ev, 1e3 * walls["step"] * 0.9, walls_sf, walls_def


def sweep(workload: str, chunks, geometry: dict, measure) -> dict:
    """One full sweep -> the COSTMODEL document (dict).  `measure` is
    real_measure(...) in production, synthetic_measure in the tier-1
    smoke."""
    import jax

    ms = {p: {} for p in PHASES}
    ms_sf = {p: {} for p in PHASES}
    ms_def = {p: {} for p in PHASES}
    events_ms = {"expand": {}, "commit": {}}
    pipe_ms = {}
    for chunk in chunks:
        t0 = time.time()
        walls, ev, pipe, walls_sf, walls_def = measure(chunk)
        for p in PHASES:
            ms[p][str(chunk)] = round(1e3 * walls[p], 4)
            ms_sf[p][str(chunk)] = round(1e3 * walls_sf[p], 4)
            ms_def[p][str(chunk)] = round(1e3 * walls_def[p], 4)
        events_ms["expand"][str(chunk)] = round(ev["expand_ms"], 4)
        events_ms["commit"][str(chunk)] = round(ev["commit_ms"], 4)
        pipe_ms[str(chunk)] = round(pipe, 4)
        print(f"  chunk {chunk}: step {ms['step'][str(chunk)]:.3f} ms "
              f"(expand {ms['expand'][str(chunk)]:.3f} / commit "
              f"{ms['commit'][str(chunk)]:.3f}; inv "
              f"{ms['inv'][str(chunk)]:.3f} sort "
              f"{ms['sort'][str(chunk)]:.3f} probe "
              f"{ms['probe'][str(chunk)]:.3f} enqueue "
              f"{ms['enqueue'][str(chunk)]:.3f}) "
              f"sort-free dedup {ms_sf['sort'][str(chunk)]:.3f} ms "
              f"deferred inv {ms_def['inv'][str(chunk)]:.3f} ms "
              f"(step {ms_def['step'][str(chunk)]:.3f}) "
              f"pipelined {pipe_ms[str(chunk)]:.3f} ms "
              f"[{time.time() - t0:.1f}s]", file=sys.stderr)
    fits = {p: fit_linear(chunks, [ms[p][str(c)] for c in chunks])
            for p in PHASES}
    fits_sf = {p: fit_linear(chunks, [ms_sf[p][str(c)] for c in chunks])
               for p in PHASES}
    fits_def = {p: fit_linear(chunks,
                              [ms_def[p][str(c)] for c in chunks])
                for p in PHASES}
    return {
        "version": COSTMODEL_VERSION,
        "workload": workload,
        "device": str(jax.devices()[0]),
        "generated_at": time.strftime("%Y-%m-%d %H:%M:%S"),
        "chunks": list(chunks),
        "geometry": dict(geometry),
        # differential sub-phase walls (obs.phases.subphase_walls)
        "ms_per_step": ms,
        # the same sweep with the sort-free hash-slab commit (ISSUE 12;
        # the "sort" column is then the slab dedup stage)
        "ms_per_step_sort_free": ms_sf,
        # the same sweep with sort-free commit AND deferred
        # invariant/cert evaluation (ISSUE 15; the "inv" column is
        # then the commit-site fresh-claimant checker)
        "ms_per_step_deferred": ms_def,
        # measured walls decoded from `phase` journal events (the
        # PhasedRuntime path a live -phase-timing run journals)
        "phase_event_ms_per_step": events_ms,
        "pipelined_step_ms": pipe_ms,
        # the PERF-style linear model: ms(chunk) = a_ms + b_ms_per_1k *
        # (chunk / 1024) per phase; slopes clamped nonnegative
        # (`clamped: true` marks a refit)
        "fit": fits,
        "fit_sort_free": fits_sf,
        "fit_deferred": fits_def,
    }


def _fit_line(fits: dict, label: str) -> str:
    return (f"fit[{label}] ms(chunk) = a + b*(chunk/1024):  "
            + "  ".join(
                f"{p} {fits[p]['a_ms']:+.3f}{fits[p]['b_ms_per_1k']:+.3f}/1k"
                + ("*" if fits[p].get("clamped") else "")
                + ("^" if fits[p].get("clamped_intercept") else "")
                for p in ("inv", "expand", "sort", "probe", "enqueue",
                          "commit")
            ))


def perf_table(doc: dict) -> str:
    """The PERF.md-ready markdown table of a sweep document.  A `*` on
    a fit marks a nonnegative-slope clamp (the raw least-squares slope
    was negative - noise through an amortized phase)."""
    chunks = doc["chunks"]
    head = ("| chunk | " + " | ".join(PHASES)
            + " | pipelined step |")
    sep = "|" + "---|" * (len(PHASES) + 2)
    rows = [head, sep]
    for c in chunks:
        cells = [f"{doc['ms_per_step'][p][str(c)]:.3f}" for p in PHASES]
        cells.append(f"{doc['pipelined_step_ms'][str(c)]:.3f}")
        rows.append(f"| {c} | " + " | ".join(cells) + " |")
    ms_sf = doc.get("ms_per_step_sort_free")
    if ms_sf:
        rows.append("")
        rows.append("sort-free commit (hash-slab dedup, same sweep):")
        rows.append(head)
        rows.append(sep)
        for c in chunks:
            cells = [f"{ms_sf[p][str(c)]:.3f}" for p in PHASES]
            cells.append(f"{doc['pipelined_step_ms'][str(c)]:.3f}")
            rows.append(f"| {c} | " + " | ".join(cells) + " |")
    ms_def = doc.get("ms_per_step_deferred")
    if ms_def:
        rows.append("")
        rows.append("deferred evaluation (sort-free + distinct-first "
                    "inv/cert, same sweep):")
        rows.append(head)
        rows.append(sep)
        for c in chunks:
            cells = [f"{ms_def[p][str(c)]:.3f}" for p in PHASES]
            cells.append(f"{doc['pipelined_step_ms'][str(c)]:.3f}")
            rows.append(f"| {c} | " + " | ".join(cells) + " |")
    rows.append("")
    rows.append(_fit_line(doc["fit"], "sorted"))
    if doc.get("fit_sort_free"):
        rows.append(_fit_line(doc["fit_sort_free"], "sort-free"))
    if doc.get("fit_deferred"):
        rows.append(_fit_line(doc["fit_deferred"], "deferred"))
    tables = (("", "fit"), (" (sort-free)", "fit_sort_free"),
              (" (deferred)", "fit_deferred"))
    clamped = [
        f"{p}{suffix}" for suffix, key in tables for p in PHASES
        if doc.get(key, {}).get(p, {}).get("clamped")
    ]
    clamped_icpt = [
        f"{p}{suffix}" for suffix, key in tables for p in PHASES
        if doc.get(key, {}).get(p, {}).get("clamped_intercept")
    ]
    if clamped:
        rows.append("* slope clamped to 0 (raw least-squares slope was "
                    f"negative): {', '.join(clamped)}")
    if clamped_icpt:
        rows.append("^ intercept clamped to 0, refit through the "
                    "origin (raw least-squares intercept was "
                    f"negative): {', '.join(clamped_icpt)}")
    return "\n".join(rows) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="costmodel")
    ap.add_argument("--chunks", default="",
                    help="comma-separated sweep (default 256,512,1024,"
                         "2048 on Model_1)")
    ap.add_argument("--workload", default="model1",
                    choices=["model1", "ff"])
    ap.add_argument("--out", default="COSTMODEL.json")
    ap.add_argument("--warm", type=int, default=32)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--loop-k", dest="K", type=int, default=4)
    ap.add_argument("--phased-steps", type=int, default=48)
    ap.add_argument("--tiny", action="store_true",
                    help="smoke the whole sweep -> fit -> JSON -> table "
                         "pipeline on the SYNTHETIC measurer (exactly "
                         "linear walls, so the fit must recover them; "
                         "no engine compiles - tier-1 budget).  The "
                         "real measurement path produces the committed "
                         "COSTMODEL.json")
    args = ap.parse_args(argv)

    from jaxtlc.config import MODEL_1, ModelConfig
    from jaxtlc.engine.backend import kubeapi_backend

    if args.tiny:
        workload = "synthetic"
        chunks = [64, 128, 256]
        geometry = {"synthetic": True}
        measure = synthetic_measure
        import tempfile

        args.out = os.path.join(tempfile.gettempdir(),
                                f"costmodel-tiny-{os.getpid()}.json")
    else:
        if args.workload == "ff":
            backend = kubeapi_backend(ModelConfig(False, False))
            workload = "Model_1_FF"
            qcap, fpcap = 1 << 13, 1 << 15
            default_chunks = "128,256,512"
        else:
            backend = kubeapi_backend(MODEL_1)
            workload = "Model_1"
            qcap, fpcap = 1 << 15, 1 << 20
            default_chunks = "256,512,1024,2048"
        chunks = [int(c) for c in
                  (args.chunks or default_chunks).split(",")]
        geometry = {"queue_capacity": qcap, "fp_capacity": fpcap,
                    "warm_steps": args.warm, "loop_K": args.K,
                    "reps": args.reps}
        measure = real_measure(backend, qcap, fpcap, args.warm,
                               args.K, args.reps, args.phased_steps)

    print(f"costmodel sweep: {workload} chunks={chunks}",
          file=sys.stderr)
    doc = sweep(workload, chunks, geometry, measure)
    tmp = args.out + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, args.out)
    print(perf_table(doc))
    if args.tiny:
        with open(args.out) as f:
            back = json.load(f)
        assert back["chunks"] == chunks
        for p in PHASES:
            assert set(back["ms_per_step"][p]) == {str(c) for c in chunks}
            # the synthetic walls are exactly linear: the fitter must
            # recover the planted coefficients - in all three modes
            for table, planted in (("fit", _SYNTH),
                                   ("fit_sort_free", _SYNTH_SF),
                                   ("fit_deferred", _SYNTH_DEF)):
                a, b = planted[p]
                fit = back[table][p]
                assert abs(fit["a_ms"] - a) < 1e-2, (table, p, fit)
                assert abs(fit["b_ms_per_1k"] - b * 1024) < 1e-2, (
                    table, p, fit)
                assert fit["r2"] > 0.999, (table, p, fit)
        # the planted sort-free dedup is 4x cheaper: the document must
        # carry the relation the acceptance gate reads off the real run
        big = str(max(chunks))
        assert back["ms_per_step"]["sort"][big] >= 2 * (
            back["ms_per_step_sort_free"]["sort"][big]
        )
        # v3: the planted deferred inv is 4x cheaper - the document
        # must carry the >= 2x relation the ISSUE 15 acceptance gate
        # reads off the real sweep
        assert back["ms_per_step_deferred"]["inv"][big] <= (
            back["ms_per_step_sort_free"]["inv"][big] / 2.0
        )
        # a decreasing series must clamp to slope 0, loudly
        cl = fit_linear([64, 128, 256], [3.0, 2.0, 1.0])
        assert cl["b_ms_per_1k"] == 0.0 and cl.get("clamped"), cl
        assert abs(cl["a_ms"] - 2.0) < 1e-9, cl
        # a negative-intercept series must clamp the intercept and
        # refit through the origin, loudly (v3: the v2 document's
        # sort a_ms = -0.4441 is the regression this guards)
        ci = fit_linear([64, 128, 256], [2.2, 5.4, 11.8])  # 0.05x - 1
        assert ci.get("clamped_intercept") and ci["a_ms"] == 0.0, ci
        assert ci["b_ms_per_1k"] > 0, ci
        assert back["phase_event_ms_per_step"]["commit"]
        assert "| chunk |" in perf_table(back)
        assert "sort-free commit" in perf_table(back)
        assert "deferred evaluation" in perf_table(back)
        os.unlink(args.out)
        print("costmodel tiny OK")
    else:
        print(f"wrote {args.out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
