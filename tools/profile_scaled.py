"""On-chip phase profiler for the scaled workload's loop body.

The tunneled TPU pays ~64 ms per dispatch, so naive per-op timing is
meaningless; every phase here runs K times inside ONE fused
``lax.fori_loop`` dispatch and the report subtracts the measured dispatch
floor.  Perf work then attacks the measured bottleneck instead of a
guessed one (VERDICT round-3 item 1).

Usage: python tools/profile_scaled.py [--chunk N] [--fpcap LOG2] [--load F]
"""

import argparse
import os
import sys
import time

# sys.path (not PYTHONPATH: the env var breaks the tunneled-TPU plugin
# discovery in this image) so the tool runs from any cwd
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jaxtlc.config import scaled_config
from jaxtlc.engine.fingerprint import fp64_words
from jaxtlc.engine.fpset import (
    BUCKET,
    _bucket_of,
    _mix,
    _remap,
    fpset_insert,
    fpset_new,
)
from jaxtlc.spec.codec import get_codec
from jaxtlc.spec.invariants import make_invariant_kernel
from jaxtlc.spec.kernel import initial_vectors, make_kernel

K = 32  # inner repetitions fused into one dispatch


def fused_time(name, body, carry, floor_s=0.0, reps=3):
    """body: carry -> carry. Times lax.fori_loop(0, K, body) per iteration."""

    @jax.jit
    def loop(c):
        return lax.fori_loop(0, K, lambda _, cc: body(cc), c)

    out = jax.block_until_ready(loop(carry))  # compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        out = jax.block_until_ready(loop(carry))
        best = min(best, time.perf_counter() - t0)
    per = (best - floor_s) / K
    if name:
        print(f"{name:36s} {per * 1e3:9.3f} ms/iter")
    return out, per


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--chunk", type=int, default=4096)
    ap.add_argument("--fpcap", type=int, default=26, help="log2 fp capacity")
    ap.add_argument("--load", type=float, default=0.29)
    args = ap.parse_args()

    cfg, _ = scaled_config()
    cdc = get_codec(cfg)
    step = make_kernel(cfg)
    L = step.n_lanes
    F = cdc.n_fields
    inv_check = make_invariant_kernel(cfg)
    chunk = args.chunk
    cap = 1 << args.fpcap
    n = chunk * L
    print(f"chunk={chunk} L={L} F={F} nbits={cdc.nbits} cand/iter={n} "
          f"fpcap=2^{args.fpcap} load={args.load} dev={jax.devices()[0]}")

    # dispatch floor: trivial fused loop
    _, floor_per = fused_time("", lambda c: c + 1, jnp.int32(0))
    floor_s = floor_per * K
    print(f"{'dispatch floor (whole loop)':36s} {floor_s * 1e3:9.3f} ms")

    # representative batch: random walk from init to get real states
    rng = np.random.default_rng(0)
    inits = jnp.asarray(initial_vectors(cfg))
    batch = jnp.tile(inits, (chunk // inits.shape[0] + 1, 1))[:chunk]
    vstep = jax.jit(jax.vmap(step))
    for _ in range(30):  # random successor walk to diversify
        succs, valid, *_ = jax.block_until_ready(vstep(batch))
        succs = np.asarray(succs)
        valid_np = np.asarray(valid)
        pick = []
        for i in range(chunk):
            idx = np.flatnonzero(valid_np[i])
            pick.append(succs[i, rng.choice(idx)] if idx.size else np.asarray(batch)[i])
        batch = jnp.asarray(np.stack(pick))

    succs0, valid0, *_ = jax.block_until_ready(vstep(batch))
    flat = jnp.reshape(succs0, (n, F))
    fvalid = jnp.reshape(valid0, (-1,))
    print(f"  valid lanes: {int(fvalid.sum())}/{n}")

    # 1. kernel expansion (carry the batch through so it isn't DCE'd)
    def b_kernel(c):
        s, v, a, af, ov = jax.vmap(step)(c)
        return c ^ s[:, 0, :1]  # cheap dependency

    _, t_kernel = fused_time("vmap(step) expansion", b_kernel, batch, floor_s)

    # 2. invariants
    def b_inv(c):
        inv = jax.vmap(inv_check)(c)
        return c ^ inv[:, None].astype(jnp.int32)

    _, t_inv = fused_time("invariant kernel", b_inv, flat, floor_s)

    # 3. pack + fingerprint
    def b_fp(c):
        packed = cdc.pack(c)
        lo, hi = fp64_words(packed, cdc.nbits)
        return c ^ lo[:, None].astype(jnp.int32)

    _, t_fp = fused_time("pack + fp64 fingerprint", b_fp, flat, floor_s)

    packed = cdc.pack(flat)
    lo, hi = fp64_words(packed, cdc.nbits)

    # table at target load with random fingerprints
    n_fill = int(cap * args.load)
    fill_lo = rng.integers(1, 1 << 32, n_fill, dtype=np.uint32)
    fill_hi = rng.integers(0, 1 << 32, n_fill, dtype=np.uint32)
    fps = fpset_new(cap)
    ins = jax.jit(fpset_insert)
    CH = 1 << 20
    for i in range(0, n_fill, CH):
        fps, _ = jax.block_until_ready(
            ins(fps, jnp.asarray(fill_lo[i:i + CH]), jnp.asarray(fill_hi[i:i + CH]),
                jnp.ones(len(fill_lo[i:i + CH]), bool)))
    print(f"  table filled to {n_fill}/{cap}")

    # 4. full fpset_insert (vary fp per rep so probes don't trivialize;
    #    table grows by ~#new per rep: negligible load change over K reps)
    def b_insert(c):
        fps_c, xlo = c
        xl = xlo ^ lo
        f2, is_new = fpset_insert(fps_c, xl, hi, fvalid)
        return (f2, xlo + jnp.uint32(1))

    _, t_ins = fused_time("fpset_insert (sort+probe)", b_insert,
                          (fps, jnp.uint32(1)), floor_s)

    # 4a. sort-dedup prefix alone
    def b_sort(c):
        xlo = c ^ lo
        inval = (~fvalid).astype(jnp.uint32)
        idx = jnp.arange(n, dtype=jnp.int32)
        s_inv, s_hi, s_lo, s_idx = lax.sort((inval, hi, xlo, idx), num_keys=3,
                                            is_stable=True)
        last = jnp.concatenate([
            (s_inv[1:] != s_inv[:-1]) | (s_hi[1:] != s_hi[:-1])
            | (s_lo[1:] != s_lo[:-1]), jnp.ones(1, bool)])
        rep_sorted = fvalid[s_idx] & last
        rep = jnp.zeros(n, bool).at[s_idx].set(rep_sorted)
        return c + rep[0].astype(jnp.uint32)

    _, t_sort = fused_time("  sort-dedup prefix", b_sort, jnp.uint32(1), floor_s)

    # 4b. one v4 bucket-probe pass (bucket gather + membership test)
    rep = fvalid

    def b_round(c):
        table, xlo = c
        l2, h2 = _mix(xlo ^ lo, hi)
        l2, h2 = _remap(l2, h2)
        bid = _bucket_of(h2, cap // BUCKET)
        bk = table[bid]  # [R, 2B] interleaved bucket rows
        hit = (bk[:, 0::2] == l2[:, None]) & (bk[:, 1::2] == h2[:, None])
        found = rep & hit.any(axis=1)
        return (table, xlo + jnp.uint32(1) + found[0].astype(jnp.uint32))

    _, t_round = fused_time("  one bucket-probe pass (gather)", b_round,
                            (fps.table, jnp.uint32(1)), floor_s)

    # 5. queue append scatter
    qcap = 1 << 21
    queue = jnp.zeros((qcap + 1, F), jnp.int32)
    is_new = fvalid

    def b_q(c):
        q, off = c
        pos = jnp.cumsum(is_new.astype(jnp.int32)) - 1 + off
        tgt = jnp.where(is_new, pos % qcap, qcap)
        return (q.at[tgt].set(flat), off + jnp.int32(7919))

    _, t_q = fused_time("queue append scatter", b_q, (queue, jnp.int32(0)), floor_s)

    total = t_kernel + t_inv + t_fp + t_ins + t_q
    print(f"{'SUM of phases':36s} {total * 1e3:9.3f} ms/iter")
    print(f"  -> at ~{chunk} distinct/iter: {chunk / total / 1e3:.1f}k distinct/s ceiling")


if __name__ == "__main__":
    main()
