#!/usr/bin/env python
"""Chaos driver: prove the supervisor's recovery paths by fault injection.

Runs a clean supervised reference run, then a series of faulted runs -
each exercising one recovery path (auto-regrow from undersized
capacities, transient-error retry, failed checkpoint write, SIGTERM
drain + resume, torn-newest-checkpoint generation fallback) - and
verifies that every recovered run's final statistics match the clean
run's EXACTLY (generated, distinct, depth, per-action counts,
outdegree).  Any mismatch is a checker bug, reported loudly with exit 1.

Usage:
    python tools/chaos.py --smoke         # fast fixed plan, CPU, FF corner
    python tools/chaos.py --plan PLAN     # custom fault plan (faults DSL)
    python tools/chaos.py --seed-caps     # also run the undersized-regrow
                                          # scenario from 1/8 capacities

The smoke mode is wired into tier-1 (tests/test_resil.py::test_chaos_smoke)
so every recovery path stays proven on every run of the suite.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sig(r):
    """The exactness signature of a CheckResult."""
    return (r.generated, r.distinct, r.depth, r.violation,
            tuple(sorted(r.action_generated.items())),
            tuple(sorted(r.action_distinct.items())),
            r.outdegree)


def run_scenarios(plan_spec: str = "", verbose: bool = True) -> int:
    """Returns 0 when every faulted run recovered to the clean run's exact
    statistics; 1 otherwise."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jaxtlc.config import ModelConfig
    from jaxtlc.resil import (
        FaultPlan,
        SupervisorOptions,
        check_supervised,
    )
    from jaxtlc.resil.faults import truncate_file
    from jaxtlc.engine.checkpoint import list_generations

    cfg = ModelConfig(False, False)  # FF corner: 17020/8203/109
    KW = dict(chunk=128, queue_capacity=1 << 12, fp_capacity=1 << 14)

    def say(msg):
        if verbose:
            print(f"[chaos] {msg}", flush=True)

    say("clean reference run...")
    clean = check_supervised(
        cfg, opts=SupervisorOptions(ckpt_every=8), **KW
    )
    want = _sig(clean.result)
    say(f"clean: generated={clean.result.generated} "
        f"distinct={clean.result.distinct} depth={clean.result.depth}")

    failures = []

    def verify(name, sr):
        got = _sig(sr.result)
        if got != want:
            failures.append(name)
            say(f"FAIL {name}: {got} != {want}")
        else:
            say(f"ok   {name} (regrows={sr.regrows} retries={sr.retries})")

    with tempfile.TemporaryDirectory() as d:
        # 1. undersized capacities -> auto-regrow to completion
        caps = dict(chunk=128, queue_capacity=1 << 9,
                    fp_capacity=1 << 11)
        say("scenario: auto-regrow from undersized capacities...")
        sr = check_supervised(
            cfg, opts=SupervisorOptions(ckpt_every=8), **caps
        )
        if sr.regrows == 0:
            failures.append("regrow(no regrow happened)")
        verify("regrow", sr)

        # 2. transient error in a segment -> backoff retry
        say("scenario: transient error at segment 1 + "
            "failed checkpoint write...")
        p2 = os.path.join(d, "t.npz")
        sr = check_supervised(
            cfg,
            opts=SupervisorOptions(
                ckpt_path=p2, ckpt_every=8, backoff_base_s=0.01,
                faults=FaultPlan.parse("transient@1,write_fail@2"),
            ),
            **KW,
        )
        if sr.retries != 1:
            failures.append("retry(no retry happened)")
        verify("transient+write_fail", sr)

        # 3. SIGTERM at segment 2 -> drain + final checkpoint; truncate the
        #    newest generation (torn write); resume falls back + completes
        say("scenario: SIGTERM drain, torn newest checkpoint, resume...")
        p3 = os.path.join(d, "s.npz")
        sr = check_supervised(
            cfg,
            opts=SupervisorOptions(
                ckpt_path=p3, ckpt_every=8,
                faults=FaultPlan.parse("sigterm@2"),
            ),
            **KW,
        )
        if not sr.interrupted:
            failures.append("sigterm(run was not interrupted)")
        gens = list_generations(p3)
        if not gens:
            failures.append("sigterm(no checkpoint generations)")
        else:
            truncate_file(gens[-1][1])
            sr = check_supervised(
                cfg,
                opts=SupervisorOptions(
                    ckpt_path=p3, ckpt_every=32, resume=True,
                ),
                **KW,
            )
            verify("sigterm+truncate+resume", sr)

        # 4. optional custom plan (--plan) against a fresh checkpoint family
        if plan_spec:
            say(f"scenario: custom plan {plan_spec!r}...")
            p4 = os.path.join(d, "c.npz")
            sr = check_supervised(
                cfg,
                opts=SupervisorOptions(
                    ckpt_path=p4, ckpt_every=8, backoff_base_s=0.01,
                    faults=FaultPlan.parse(plan_spec),
                ),
                **KW,
            )
            if sr.interrupted:
                sr = check_supervised(
                    cfg,
                    opts=SupervisorOptions(
                        ckpt_path=p4, ckpt_every=32, resume=True,
                    ),
                    **KW,
                )
            verify(f"custom({plan_spec})", sr)

    if failures:
        say(f"FAILURES: {failures}")
        return 1
    say("all recovery paths recovered to exact clean-run statistics")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="fault-injection chaos driver for the run supervisor"
    )
    p.add_argument("--smoke", action="store_true",
                   help="fast fixed-plan CPU run (the tier-1 wiring)")
    p.add_argument("--plan", default="",
                   help="extra fault plan DSL for a custom scenario")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)
    return run_scenarios(plan_spec=args.plan, verbose=not args.quiet)


if __name__ == "__main__":
    sys.exit(main())
