#!/usr/bin/env python
"""Chaos driver: prove the supervisor's recovery paths by fault injection.

Runs a clean supervised reference run, then a series of faulted runs -
each exercising one recovery path (auto-regrow from undersized
capacities, transient-error retry, failed checkpoint write, SIGTERM
drain + resume, torn-newest-checkpoint generation fallback) - and
verifies that every recovered run's final statistics match the clean
run's EXACTLY (generated, distinct, depth, per-action counts,
outdegree).  Any mismatch is a checker bug, reported loudly with exit 1.

Usage:
    python tools/chaos.py --smoke         # fast fixed plan, CPU, FF corner
    python tools/chaos.py --plan PLAN     # custom fault plan (faults DSL)
    python tools/chaos.py --seed-caps     # also run the undersized-regrow
                                          # scenario from 1/8 capacities
    python tools/chaos.py --matrix --tiny # degradation-ladder matrix:
                                          # every rung of the capacity
                                          # ladder pinned bit-for-bit
    python tools/chaos.py --serve --tiny  # serving-layer matrix: the
                                          # scheduler's overload paths
                                          # under injected faults
                                          # (ISSUE 17; zero compiles)

The smoke mode is wired into tier-1 (tests/test_resil.py::test_chaos_smoke)
and the ladder matrix into tests/test_spill.py, so every recovery path
stays proven on every run of the suite.

The ladder matrix (ISSUE 7): each scenario denies a capacity-recovery
step by fault injection and verifies the supervisor lands on the NEXT
rung with clean-run-exact final statistics:

    regrow denied (alloc_fail@1)   -> host spill tier completes the run
    spill + SIGTERM                -> -recover restores BOTH tiers
    spill write fails (spill_fail) -> checkpoint + exhausted (exit 75),
                                      resume completes
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sig(r):
    """The exactness signature of a CheckResult."""
    return (r.generated, r.distinct, r.depth, r.violation,
            tuple(sorted(r.action_generated.items())),
            tuple(sorted(r.action_distinct.items())),
            r.outdegree)


def run_scenarios(plan_spec: str = "", verbose: bool = True) -> int:
    """Returns 0 when every faulted run recovered to the clean run's exact
    statistics; 1 otherwise."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jaxtlc.config import ModelConfig
    from jaxtlc.resil import (
        FaultPlan,
        SupervisorOptions,
        check_supervised,
    )
    from jaxtlc.resil.faults import truncate_file
    from jaxtlc.engine.checkpoint import list_generations

    cfg = ModelConfig(False, False)  # FF corner: 17020/8203/109
    KW = dict(chunk=128, queue_capacity=1 << 12, fp_capacity=1 << 14)

    def say(msg):
        if verbose:
            print(f"[chaos] {msg}", flush=True)

    say("clean reference run...")
    clean = check_supervised(
        cfg, opts=SupervisorOptions(ckpt_every=8), **KW
    )
    want = _sig(clean.result)
    say(f"clean: generated={clean.result.generated} "
        f"distinct={clean.result.distinct} depth={clean.result.depth}")

    failures = []

    def verify(name, sr):
        got = _sig(sr.result)
        if got != want:
            failures.append(name)
            say(f"FAIL {name}: {got} != {want}")
        else:
            say(f"ok   {name} (regrows={sr.regrows} retries={sr.retries})")

    with tempfile.TemporaryDirectory() as d:
        # 1. undersized capacities -> auto-regrow to completion
        caps = dict(chunk=128, queue_capacity=1 << 9,
                    fp_capacity=1 << 11)
        say("scenario: auto-regrow from undersized capacities...")
        sr = check_supervised(
            cfg, opts=SupervisorOptions(ckpt_every=8), **caps
        )
        if sr.regrows == 0:
            failures.append("regrow(no regrow happened)")
        verify("regrow", sr)

        # 2. transient error in a segment -> backoff retry
        say("scenario: transient error at segment 1 + "
            "failed checkpoint write...")
        p2 = os.path.join(d, "t.npz")
        sr = check_supervised(
            cfg,
            opts=SupervisorOptions(
                ckpt_path=p2, ckpt_every=8, backoff_base_s=0.01,
                faults=FaultPlan.parse("transient@1,write_fail@2"),
            ),
            **KW,
        )
        if sr.retries != 1:
            failures.append("retry(no retry happened)")
        verify("transient+write_fail", sr)

        # 3. SIGTERM at segment 2 -> drain + final checkpoint; truncate the
        #    newest generation (torn write); resume falls back + completes
        say("scenario: SIGTERM drain, torn newest checkpoint, resume...")
        p3 = os.path.join(d, "s.npz")
        sr = check_supervised(
            cfg,
            opts=SupervisorOptions(
                ckpt_path=p3, ckpt_every=8,
                faults=FaultPlan.parse("sigterm@2"),
            ),
            **KW,
        )
        if not sr.interrupted:
            failures.append("sigterm(run was not interrupted)")
        gens = list_generations(p3)
        if not gens:
            failures.append("sigterm(no checkpoint generations)")
        else:
            truncate_file(gens[-1][1])
            sr = check_supervised(
                cfg,
                opts=SupervisorOptions(
                    ckpt_path=p3, ckpt_every=32, resume=True,
                ),
                **KW,
            )
            verify("sigterm+truncate+resume", sr)

        # 4. optional custom plan (--plan) against a fresh checkpoint family
        if plan_spec:
            say(f"scenario: custom plan {plan_spec!r}...")
            p4 = os.path.join(d, "c.npz")
            sr = check_supervised(
                cfg,
                opts=SupervisorOptions(
                    ckpt_path=p4, ckpt_every=8, backoff_base_s=0.01,
                    faults=FaultPlan.parse(plan_spec),
                ),
                **KW,
            )
            if sr.interrupted:
                sr = check_supervised(
                    cfg,
                    opts=SupervisorOptions(
                        ckpt_path=p4, ckpt_every=32, resume=True,
                    ),
                    **KW,
                )
            verify(f"custom({plan_spec})", sr)

    if failures:
        say(f"FAILURES: {failures}")
        return 1
    say("all recovery paths recovered to exact clean-run statistics")
    return 0


def run_matrix(tiny: bool = True, verbose: bool = True,
               artifacts_dir: str = None):
    """The degradation-ladder matrix: every rung triggered by injected
    faults, every recovered run verified bit-for-bit against a clean
    run at the SAME chunk (chunk batching shapes in-batch attribution,
    so the reference must match it).  Returns (rc, details): details
    carries per-scenario signatures, captured journal events, and the
    spill scenario's journal path (tests assert schema validity and
    the tlcstat rendering on it).

    `tiny` picks the FF corner at small capacities (the tier-1 wiring;
    there is no big mode yet - the flag keeps the CLI contract stable
    when a Model_1-scale matrix lands behind it)."""
    import contextlib
    import jax

    jax.config.update("jax_platforms", "cpu")
    from jaxtlc.config import ModelConfig
    from jaxtlc.engine.bfs import check
    from jaxtlc.obs.journal import RunJournal
    from jaxtlc.resil import (
        FaultPlan,
        SupervisorOptions,
        check_supervised,
    )

    cfg = ModelConfig(False, False)  # FF corner: 17020/8203/109
    chunk = 64 if tiny else 128
    # undersized on purpose; the counter ring rides along so the
    # spill-hit column (obs COL_SPILL) lands in the level events
    caps = dict(chunk=chunk, queue_capacity=1 << 7,
                fp_capacity=1 << 11, obs_slots=32)

    def say(msg):
        if verbose:
            print(f"[chaos-matrix] {msg}", flush=True)

    say(f"clean reference (chunk={chunk})...")
    clean = check(cfg, chunk=chunk, queue_capacity=1 << 12,
                  fp_capacity=1 << 14)
    details = {"clean_sig": _sig(clean), "scenarios": {}}
    failures = []

    def run(name, faults, ckpt, journal=None, resume=False):
        events = []

        def on_event(kind, info):
            if journal is not None:
                events.append(journal.event(kind, **info))
            else:
                events.append({"event": kind, **info})

        sr = check_supervised(
            cfg, opts=SupervisorOptions(
                ckpt_path=ckpt, ckpt_every=8, resume=resume,
                faults=FaultPlan.parse(faults) if faults else None,
                on_event=on_event,
            ), **caps,
        )
        details["scenarios"][name] = {
            "sig": _sig(sr.result), "events": events,
            "regrows": sr.regrows, "spilled": sr.spilled,
            "spill_flushes": sr.spill_flushes,
            "spill_hits": sr.spill_hits,
            "interrupted": sr.interrupted, "exhausted": sr.exhausted,
        }
        return sr

    def verify(name, sr, want_complete=True):
        if want_complete and _sig(sr.result) != details["clean_sig"]:
            failures.append(f"{name}(signature mismatch)")
            say(f"FAIL {name}: {_sig(sr.result)} != "
                f"{details['clean_sig']}")
        elif want_complete:
            say(f"ok   {name} (regrows={sr.regrows} "
                f"spilled={sr.spilled} flushes={sr.spill_flushes} "
                f"hits={sr.spill_hits})")

    own_dir = None
    if artifacts_dir is None:
        own_dir = tempfile.TemporaryDirectory()
        artifacts_dir = own_dir.name
    with contextlib.ExitStack() as stack:
        if own_dir is not None:
            stack.enter_context(own_dir)

        # rung 2 + recover: regrow denied -> spill tier; SIGTERM mid-
        # spill -> drain; -recover restores BOTH tiers and completes
        # with clean statistics (undersized queue also forces a queue
        # regrow WHILE the spill tier is active)
        say("scenario: regrow denied -> spill; SIGTERM; recover...")
        ck1 = os.path.join(artifacts_dir, "ladder-spill.npz")
        jpath = ck1 + ".journal.jsonl"
        j = stack.enter_context(RunJournal(jpath))
        sr = run("spill-sigterm", "alloc_fail@1,sigterm@6", ck1,
                 journal=j)
        sc = details["scenarios"]["spill-sigterm"]
        if not sr.interrupted:
            failures.append("spill-sigterm(not interrupted)")
        if sr.spilled == 0:
            failures.append("spill-sigterm(spill tier never activated)")
        if not os.path.exists(ck1 + ".spill"):
            failures.append("spill-sigterm(no host-tier sibling file)")
        j.event("run_resume", version="chaos-matrix", path=jpath)
        sr = run("spill-recover", "", ck1, journal=j, resume=True)
        # the undersized queue must have regrown WHILE the spill tier
        # was active, in whichever attempt the wide level landed in
        # (the grown geometry travels inside the checkpoint)
        if sc["regrows"] + sr.regrows == 0:
            failures.append(
                "spill-recover(no queue regrow under spill)"
            )
        verify("spill-recover", sr)
        details["journal_path"] = jpath

        # rung 4: the spill write itself fails -> checkpoint +
        # exhausted (exit 75 at the CLI) with a verified resumable
        # generation on disk (the resume path itself is the one
        # spill-recover just proved; re-running it would only re-pay
        # an engine compile against the tier-1 wall-clock budget)
        say("scenario: spill write fails -> exhausted...")
        ck2 = os.path.join(artifacts_dir, "ladder-exhaust.npz")
        sr = run("spill-fail", "alloc_fail@1,spill_fail@1", ck2)
        if not (sr.exhausted and sr.interrupted):
            failures.append("spill-fail(did not exhaust)")
        if not any(e["event"] == "exhausted"
                   for e in details["scenarios"]["spill-fail"]["events"]):
            failures.append("spill-fail(no exhausted event)")
        from jaxtlc.engine.checkpoint import (
            list_generations,
            read_checkpoint_meta,
        )

        gens = list_generations(ck2)
        if not gens:
            failures.append("spill-fail(no checkpoint generation)")
        else:
            meta = read_checkpoint_meta(gens[-1][1])
            if not (meta.get("spill") or {}).get("active"):
                failures.append("spill-fail(meta lost the spill tier)")

        if sc["spill_hits"] == 0 and \
                details["scenarios"]["spill-recover"]["spill_hits"] == 0:
            failures.append("matrix(host tier never vetoed a candidate)")

    if failures:
        say(f"FAILURES: {failures}")
        return 1, details
    say("ladder matrix: every rung recovered to exact clean statistics")
    return 0, details


def run_serve(tiny: bool = True, verbose: bool = True) -> int:
    """The serving-layer chaos matrix (ISSUE 17): a real CheckServer
    over a STUB runner (no engines, ZERO XLA compiles) with scheduler
    faults injected - `runner_die@N` kills a dispatch with a transient
    fault the retry classification must absorb, `slow_dispatch@N`
    stalls the worker to open a deterministic overload window - and
    the whole outcome matrix driven through the real HTTP surface:
    retry-to-done, queued-deadline expiry, admission 429, cancel,
    breaker quarantine.  The three liveness invariants under test:

    * the queue never wedges - every admitted job reaches a terminal
      state and a post-storm drain() completes;
    * every rejection is a 429 carrying a Retry-After hint;
    * an SSE follower terminates on EVERY outcome class (done /
      expired / canceled / quarantined), because even never-ran jobs
      get a minimal journal with a final event.
    """
    import threading
    import time

    from jaxtlc.obs import journal as obs_journal
    from jaxtlc.serve import client
    from jaxtlc.serve.scheduler import TERMINAL_STATES
    from jaxtlc.serve.server import CheckServer

    def say(msg):
        if verbose:
            print(f"[chaos-serve] {msg}", flush=True)

    SPEC = ("---- MODULE ServeChaos ----\nVARIABLE x\nInit == x = 0\n"
            "Next == x' = x\n====\n")
    CFG = "SPECIFICATION\nSpec\n"
    POISON_SPEC = ("---- MODULE ServePoison ----\nVARIABLE x\n"
                   "Init == x = 0\nNext == x' = x\n====\n")

    class _StubPool:
        """Engine-pool stand-in: the chaos matrix tests scheduling
        POLICY, so dispatches must cost microseconds, not compiles."""

        sweep_width = 4

        def stats(self):
            return dict(hits=0, misses=0, size=0, compiles=0,
                        entries=[])

        def shutdown(self):
            pass

    failures = []
    srv = CheckServer(
        pool=_StubPool(), queue_bound=3, breaker_threshold=2,
        breaker_cooldown_s=3600.0,
        faults="runner_die@2,slow_dispatch@4",
    )
    sch = srv.scheduler
    sch._injector.slow_dispatch_s = 1.0  # the overload window

    def stub_run(batch):
        for j in batch:
            if j.name.startswith("poison"):
                raise ValueError("injected poison dispatch")
            with sch._journal(j) as jr:
                jr.event("run_start", version="chaos-serve",
                         workload=j.name, engine="stub", device="host",
                         params={})
                jr.event("final", verdict="ok", generated=1,
                         distinct=1, depth=1, queue=0, wall_s=0.0,
                         interrupted=False)
            sch._finish_ok(j, dict(verdict="ok", engine="stub",
                                   generated=1, distinct=1, depth=1,
                                   wall_s=0.0))

    sch._run_batch = stub_run

    verdicts = {}

    def follow(job_id):
        """SSE follower: retries until the job's journal exists (a
        never-ran job only gets one at its terminal transition), then
        records the final verdict.  MUST terminate - that is the
        invariant under test."""
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                for ev in client.stream(srv.url, job_id, timeout=30):
                    if ev.get("event") == "final":
                        verdicts[job_id] = ev["verdict"]
                        return
            except Exception:
                time.sleep(0.02)
        verdicts[job_id] = None  # follower wedged

    followers = []

    def check(cond, what):
        if not cond:
            failures.append(what)
            say(f"FAIL {what}")

    try:
        # dispatch 1: a clean job through the stub runner
        say("clean stub job...")
        a = client.check(srv.url, SPEC, CFG, name="serve-a")
        check(a["state"] == "done", "clean(job not done)")

        # dispatch 2 = runner_die -> retry -> dispatch 3 completes
        say("runner_die@2: dispatch dies, retry must absorb it...")
        b = client.check(srv.url, SPEC, CFG, name="serve-b")
        check(b["state"] == "done", "retry(job not done)")
        check(b.get("retries") == 1,
              f"retry(retries={b.get('retries')}, want 1)")

        # dispatch 4 = slow_dispatch: the worker stalls 1 s - the
        # deterministic overload window for deadline/admission/cancel
        say("slow_dispatch@4: stall the worker, storm the queue...")
        c_id = client.submit(srv.url, SPEC, CFG, name="serve-c")
        dl = time.time() + 10
        while client.status(srv.url, c_id)["state"] != "running":
            check(time.time() < dl, "window(dispatch never started)")
            if failures:
                break
            time.sleep(0.005)
        d_id = client.submit(srv.url, SPEC, CFG, name="serve-d",
                             options={"deadline_s": 0.3})
        e_id = client.submit(srv.url, SPEC, CFG, name="serve-e")
        f_id = client.submit(srv.url, SPEC, CFG, name="serve-f")
        for jid in (c_id, d_id, e_id):
            t = threading.Thread(target=follow, args=(jid,),
                                 daemon=True)
            t.start()
            followers.append(t)
        # queue is at the bound: the next submit must be a 429
        try:
            client.submit(srv.url, SPEC, CFG, name="serve-g",
                          retries=0)
            check(False, "admission(over-bound submit accepted)")
        except client.ClientError as e:
            check(e.code == 429, f"admission(code={e.code})")
            check((e.retry_after or 0) >= 1,
                  f"admission(retry_after={e.retry_after})")
        h = client.health(srv.url)
        check(h["status"] == "overloaded",
              f"health(status={h['status']} under full queue)")
        canceled = client.cancel(srv.url, e_id)
        check(canceled["state"] == "canceled",
              f"cancel(state={canceled['state']})")
        d = client.wait(srv.url, d_id, timeout=10)
        check(d["state"] == "expired", f"deadline(state={d['state']})")
        c = client.wait(srv.url, c_id, timeout=10)
        check(c["state"] == "done", f"window(c state={c['state']})")
        f = client.wait(srv.url, f_id, timeout=10)
        check(f["state"] == "done", f"window(f state={f['state']})")

        # breaker: two poison dispatches trip the digest breaker; the
        # third submit of the same spec is quarantined WITHOUT running
        say("poison spec: trip the breaker, quarantine the third...")
        for i in (1, 2):
            p = client.check(srv.url, POISON_SPEC, CFG,
                             name=f"poison-{i}")
            check(p["state"] == "error", f"poison-{i}({p['state']})")
        q = client.check(srv.url, POISON_SPEC, CFG, name="poison-3")
        check(q["state"] == "quarantined",
              f"quarantine(state={q['state']})")
        t = threading.Thread(target=follow, args=(q["id"],),
                             daemon=True)
        t.start()
        followers.append(t)

        # post-storm: the queue must still schedule, across tenants
        say("post-storm drain across two tenants...")
        ids = [client.submit(srv.url, SPEC, CFG, name=f"post-{i}",
                             tenant=("ci" if i % 2 else "dev"))
               for i in range(4)]
        for jid in ids:
            st = client.wait(srv.url, jid, timeout=10)
            check(st["state"] == "done", f"post({jid}={st['state']})")

        check(sch.drain(timeout=10) is True, "drain(did not complete)")
        h = client.health(srv.url)
        check(h["status"] == "ok" and h["queued"] == 0,
              f"health(end={h['status']}/{h['queued']})")
        for k in ("rejected", "expired", "canceled", "quarantined",
                  "retried"):
            check(h["counters"][k] >= 1, f"counters({k}=0)")
        nonterminal = [j["id"] for j in sch.list()
                       if j["state"] not in TERMINAL_STATES]
        check(not nonterminal, f"wedge(nonterminal={nonterminal})")

        for t in followers:
            t.join(timeout=30)
        check(not any(t.is_alive() for t in followers),
              "sse(a follower never terminated)")
        want = {c_id: "ok", d_id: "expired", e_id: "canceled",
                q["id"]: "quarantined"}
        for jid, v in want.items():
            check(verdicts.get(jid) == v,
                  f"sse({jid}: {verdicts.get(jid)} != {v})")
        sched_journal = os.path.join(srv.root, "sched.journal.jsonl")
    finally:
        srv.shutdown()

    # the control plane's own journal is schema-valid and carries
    # every decision class this storm exercised
    events = obs_journal.read(sched_journal)
    actions = {e["action"] for e in events if e["event"] == "sched"}
    missing = {"admit", "dispatch", "retry", "reject", "expire",
               "cancel", "quarantine"} - actions
    if missing:
        failures.append(f"journal(missing actions {sorted(missing)})")
        say(f"FAIL journal(missing actions {sorted(missing)})")

    if failures:
        say(f"FAILURES: {failures}")
        return 1
    say("chaos serve OK: retry absorbed, deadline expired, 429 + "
        "Retry-After on overload, cancel + quarantine terminal, SSE "
        "followers terminated on every outcome, queue drained clean")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="fault-injection chaos driver for the run supervisor"
    )
    p.add_argument("--smoke", action="store_true",
                   help="fast fixed-plan CPU run (the tier-1 wiring)")
    p.add_argument("--matrix", action="store_true",
                   help="degradation-ladder matrix: deny each capacity-"
                        "recovery step by fault injection, verify the "
                        "next rung lands bit-for-bit on clean stats")
    p.add_argument("--serve", action="store_true",
                   help="serving-layer matrix (ISSUE 17): scheduler "
                        "fault injection (runner_die, slow_dispatch) "
                        "against a stub runner - retry, deadline, "
                        "admission 429, cancel, quarantine, SSE "
                        "termination; ZERO XLA compiles")
    p.add_argument("--tiny", action="store_true",
                   help="with --matrix/--serve: the tier-1 wiring")
    p.add_argument("--plan", default="",
                   help="extra fault plan DSL for a custom scenario")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)
    if args.serve:
        return run_serve(tiny=args.tiny, verbose=not args.quiet)
    if args.matrix:
        rc, _ = run_matrix(tiny=args.tiny, verbose=not args.quiet)
        return rc
    return run_scenarios(plan_spec=args.plan, verbose=not args.quiet)


if __name__ == "__main__":
    sys.exit(main())
