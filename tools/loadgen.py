"""Load generator for the checking service (ISSUE 9 CI tooling).

Submits N jobs against a live `jaxtlc.serve` server (or an in-process
one it starts itself), asserts the pool-reuse contract - every submit
after the first of a (spec, constants-class, geometry) is a pool HIT
and the warm path performs ZERO fresh XLA compiles - and reports
latency percentiles for the warm path plus the batched-sweep
throughput ratio.

    python tools/loadgen.py --url http://HOST:PORT --jobs 32
    python tools/loadgen.py --tiny     # self-contained; wired into
                                       # tier-1 next to the serve and
                                       # costmodel tiny smokes

The tiny mode is the serving analog of `tools/chaos.py --matrix`: one
driver invocation that exercises submit -> schedule -> pool ->
sweep-batch -> journal -> /runs end to end and fails loudly if the
warm path regresses into recompiles.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

_SPEC = """---- MODULE LoadTiny ----
EXTENDS Naturals
CONSTANTS MAX
VARIABLES x, y

Init == /\\ x = 0
        /\\ y = 0

Up == /\\ x < MAX
      /\\ x' = x + 1
      /\\ y' = y

Flip == /\\ x > 0
        /\\ y' = 1 - y
        /\\ x' = x

Next == Up \\/ Flip

Spec == Init /\\ [][Next]_<<x, y>>

InRange == x <= MAX
====
"""

_CFG = """CONSTANT MAX = 4
SPECIFICATION
Spec
INVARIANT
InRange
"""


def _pct(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    k = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[k]


def run_load(url: str, jobs: int, sweep_jobs: int,
             out=sys.stdout) -> dict:
    """Drive `url`: one cold submit, `jobs - 1` warm resubmits, then
    `sweep_jobs` batched sweep submits.  Returns the report dict."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(chunk=16, qcap=256, fpcap=1024)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="load-cold",
                        options=opts)
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["verdict"] == "ok", cold

    warm_lat = []
    pre_compiles = xla_compiles()
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"load-warm-{i}",
                          options=opts)
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["pool_hit"] is True, st
        assert st["result"]["generated"] == cold["result"]["generated"]
    fresh = xla_compiles() - pre_compiles
    assert fresh == 0, f"warm path paid {fresh} fresh XLA compiles"

    # batched sweep: K configs of the class through one dispatch
    sweep = {"const": "MAX", "lo": 1, "hi": 4}
    ids = [
        client.submit(url, _SPEC, _CFG, name=f"load-sweep-{v}",
                      constants={"MAX": 1 + (v % 4)}, sweep=sweep,
                      options=opts)
        for v in range(sweep_jobs)
    ]
    t0 = time.time()
    sts = [client.wait(url, i, timeout=600) for i in ids]
    sweep_s = time.time() - t0
    for st in sts:
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sweep", st

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs, sweep_jobs=sweep_jobs,
        cold_s=round(cold_s, 4),
        warm_p50_s=round(_pct(warm_lat, 0.50), 4),
        warm_p95_s=round(_pct(warm_lat, 0.95), 4),
        warm_fresh_xla_compiles=fresh,
        sweep_wall_s=round(sweep_s, 4),
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"],
                  compiles=stats["pool"]["compiles"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_cache(url: str, jobs: int, in_process: bool,
              out=sys.stdout) -> dict:
    """The --cache mode (ISSUE 13): N IDENTICAL submits against the
    artifact cache.  Submit 1 is the cold population run; submits 2..N
    must be verdict-tier hits - ZERO fresh XLA compiles (CompileMeter)
    AND zero engine dispatches (the pool entry's use count freezes) -
    and their p50/p95 latency is the O(HTTP) number PERF.md round 16
    compares against the 54 ms warm-pool submit."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(chunk=16, qcap=256, fpcap=1024)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="cache-cold",
                        options=opts)
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["verdict"] == "ok", cold
    assert cold["result"]["engine"] == "pool", cold

    def pool_uses():
        # every pooled dispatch is preceded by exactly one pool lookup
        # (uses counts hits; the cold build's own run is covered by
        # the miss/build counters): frozen uses == zero dispatches
        st = client.pool_stats(url)
        return (sum(e["uses"] for e in st["pool"]["entries"])
                + st["pool"]["misses"])

    uses0 = pool_uses()
    pre = xla_compiles() if in_process else None
    hit_lat = []
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        # fine-grained poll (5 ms vs the default 50): the hit path is
        # O(HTTP), so the default poll interval would BE the number
        st = client.wait(
            url,
            client.submit(url, _SPEC, _CFG, name=f"cache-hit-{i}",
                          options=opts),
            poll_s=0.005,
        )
        hit_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "cache", st
        assert st["result"].get("cache_hit") is True, st
        assert st["result"]["generated"] == cold["result"]["generated"]
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, f"cache-hit path paid {fresh} fresh XLA compiles"
    dispatches = pool_uses() - uses0
    assert dispatches == 0, (
        f"cache-hit path dispatched {dispatches} engine run(s)"
    )
    stats = client.pool_stats(url)
    cache = client._get(url + "/cache")
    report = dict(
        jobs=jobs,
        cold_s=round(cold_s, 4),
        hit_p50_s=round(_pct(hit_lat, 0.50), 4),
        hit_p95_s=round(_pct(hit_lat, 0.95), 4),
        hit_fresh_xla_compiles=fresh,
        hit_engine_dispatches=dispatches,
        scheduler_cache_hits=stats["scheduler"]["cache_hits"],
        store=cache["stats"] if cache.get("enabled") else None,
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_sim_load(url: str, jobs: int, in_process: bool,
                 out=sys.stdout) -> dict:
    """The --sim mode (ISSUE 14): the smoke job class under load.
    Submit 1 cold + N-1 warm sim jobs (same spec, DIFFERENT seeds -
    the seed is a batch lane, not key material, so every resubmit
    after the first must be a pool HIT with ZERO fresh XLA compiles),
    then one folded burst submitted together to exercise the vmapped
    seed batch."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(simulate=True, walkers=16, depth=32, fpcap=1024,
                nodeadlock=True)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="sim-cold",
                        options=dict(opts, simseed=0))
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["engine"] == "sim", cold
    assert cold["result"]["verdict"] == "ok", cold

    warm_lat = []
    pre = xla_compiles() if in_process else None
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"sim-warm-{i}",
                          options=dict(opts, simseed=i + 1))
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sim", st
        assert st["result"]["pool_hit"] is True, st
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, f"warm sim path paid {fresh} fresh XLA compiles"

    # a burst submitted together folds into vmapped seed batches
    ids = [client.submit(url, _SPEC, _CFG, name=f"sim-burst-{i}",
                         options=dict(opts, simseed=100 + i))
           for i in range(jobs)]
    t0 = time.time()
    sts = [client.wait(url, i, timeout=600) for i in ids]
    burst_s = time.time() - t0
    for st in sts:
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sim", st

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs,
        cold_s=round(cold_s, 4),
        sim_p50_s=round(_pct(warm_lat, 0.50), 4),
        sim_p95_s=round(_pct(warm_lat, 0.95), 4),
        sim_fresh_xla_compiles=fresh,
        burst_wall_s=round(burst_s, 4),
        transitions=cold["result"]["sim"]["transitions"],
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_infer_load(url: str, jobs: int, in_process: bool,
                   out=sys.stdout) -> dict:
    """The --infer mode (ISSUE 16): the inference job class under
    load.  Submit 1 cold + N-1 warm infer jobs (same spec, DIFFERENT
    seeds - the seed only drives sampled evidence, not key material,
    so every resubmit after the first must be a pool HIT with ZERO
    fresh XLA compiles)."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(infer=True, inferbudget=16, walkers=16, depth=32,
                nodeadlock=True)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="infer-cold",
                        options=dict(opts, simseed=0))
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["engine"] == "infer", cold
    assert cold["result"]["verdict"] == "ok", cold
    funnel = cold["result"]["infer"]
    assert funnel["candidates"] > 0, funnel

    warm_lat = []
    pre = xla_compiles() if in_process else None
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"infer-warm-{i}",
                          options=dict(opts, simseed=i + 1))
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "infer", st
        assert st["result"]["pool_hit"] is True, st
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, (
        f"warm infer path paid {fresh} fresh XLA compiles"
    )

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs,
        cold_s=round(cold_s, 4),
        infer_p50_s=round(_pct(warm_lat, 0.50), 4),
        infer_p95_s=round(_pct(warm_lat, 0.95), 4),
        infer_fresh_xla_compiles=fresh,
        candidates=funnel["candidates"],
        survivors=funnel["survivors"],
        certified=len(funnel["certified"]),
        evidence=funnel["evidence"],
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="loadgen")
    p.add_argument("--url", default="",
                   help="a live jaxtlc.serve server; default: start "
                        "one in-process")
    p.add_argument("--jobs", type=int, default=8,
                   help="plain submits of one model (1 cold + N-1 warm)")
    p.add_argument("--sweep-jobs", type=int, default=4,
                   help="sweep submits folded into batched dispatches")
    p.add_argument("--sim", action="store_true",
                   help="smoke job class mode (ISSUE 14): 1 cold + "
                        "N-1 warm sim submits (different seeds, same "
                        "warm engine - zero fresh XLA compiles "
                        "asserted) plus a folded seed-batch burst; "
                        "reports warm sim p50/p95")
    p.add_argument("--infer", action="store_true",
                   help="inference job class mode (ISSUE 16): 1 cold "
                        "+ N-1 warm infer submits (different evidence "
                        "seeds, same warm engine - zero fresh XLA "
                        "compiles asserted); reports warm infer "
                        "p50/p95 and the candidate funnel")
    p.add_argument("--cache", action="store_true",
                   help="incremental re-checking mode (ISSUE 13): N "
                        "identical submits; 1 cold population run, "
                        "N-1 verdict-tier hits asserted to perform "
                        "ZERO fresh XLA compiles and ZERO engine "
                        "dispatches; reports hit p50/p95.  In-process "
                        "servers get a temp store so the run is "
                        "self-contained")
    p.add_argument("--tiny", action="store_true",
                   help="tier-1 smoke: in-process server, 4 plain + 4 "
                        "sweep jobs, pool-reuse + zero-compile "
                        "assertions (with --cache: 4 identical "
                        "submits through the artifact cache)")
    args = p.parse_args(argv)
    if args.tiny:
        args.jobs, args.sweep_jobs, args.url = 4, 4, ""

    srv = None
    url = args.url
    token = None
    try:
        if not url:
            if args.cache:
                # self-contained store: the assertions need a cache
                # that starts empty and nothing else writes to
                import tempfile

                from jaxtlc.struct import artifacts as arts

                token = arts.configure(
                    tempfile.mkdtemp(prefix="jaxtlc-loadgen-cache-")
                )
            from jaxtlc.serve.server import start_server

            srv = start_server(sweep_width=4)
            url = srv.url
        if args.sim:
            report = run_sim_load(url, args.jobs,
                                  in_process=srv is not None)
            ok = (report["sim_fresh_xla_compiles"] == 0
                  and report["pool"]["hits"] >= args.jobs - 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: "
                  f"{args.jobs} sim submits (1 cold + "
                  f"{args.jobs - 1} warm) + {args.jobs} burst, "
                  f"warm sim p50 {report['sim_p50_s'] * 1000:.1f} ms "
                  f"/ p95 {report['sim_p95_s'] * 1000:.1f} ms, "
                  f"0 fresh compiles on the warm path, "
                  f"{report['scheduler']['batched_jobs']} jobs "
                  f"through {report['scheduler']['batches_run']} "
                  "dispatches")
            return 0 if ok else 1
        if args.infer:
            report = run_infer_load(url, args.jobs,
                                    in_process=srv is not None)
            ok = (report["infer_fresh_xla_compiles"] == 0
                  and report["pool"]["hits"] >= args.jobs - 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: "
                  f"{args.jobs} infer submits (1 cold + "
                  f"{args.jobs - 1} warm), "
                  f"{report['candidates']} candidates -> "
                  f"{report['survivors']} survive -> "
                  f"{report['certified']} certified "
                  f"[{report['evidence']} evidence], "
                  f"warm infer p50 "
                  f"{report['infer_p50_s'] * 1000:.1f} ms "
                  f"/ p95 {report['infer_p95_s'] * 1000:.1f} ms, "
                  f"0 fresh compiles on the warm path")
            return 0 if ok else 1
        if args.cache:
            report = run_cache(url, args.jobs, in_process=srv is not None)
            ok = (report["hit_fresh_xla_compiles"] == 0
                  and report["hit_engine_dispatches"] == 0
                  and report["scheduler_cache_hits"] >= args.jobs - 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: "
                  f"{args.jobs} identical submits, 1 cold + "
                  f"{args.jobs - 1} verdict-tier hits, hit p50 "
                  f"{report['hit_p50_s'] * 1000:.1f} ms / p95 "
                  f"{report['hit_p95_s'] * 1000:.1f} ms, 0 fresh "
                  f"compiles and 0 engine dispatches on the hit path")
            return 0 if ok else 1
        report = run_load(url, args.jobs, args.sweep_jobs)
    finally:
        if srv is not None:
            srv.shutdown()
        if token is not None:
            from jaxtlc.struct import artifacts as arts

            arts.restore(token)
    ok = (report["warm_fresh_xla_compiles"] == 0
          and report["pool"]["hits"] >= args.jobs - 1)
    print(f"loadgen {'OK' if ok else 'FAILED'}: "
          f"{args.jobs} plain + {args.sweep_jobs} sweep jobs, "
          f"warm p50 {report['warm_p50_s'] * 1000:.1f} ms / "
          f"p95 {report['warm_p95_s'] * 1000:.1f} ms, "
          f"0 fresh compiles on the warm path, "
          f"{report['scheduler']['batched_jobs']} jobs through "
          f"{report['scheduler']['batches_run']} sweep dispatches")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
