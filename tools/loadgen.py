"""Load generator for the checking service (ISSUE 9 CI tooling).

Submits N jobs against a live `jaxtlc.serve` server (or an in-process
one it starts itself), asserts the pool-reuse contract - every submit
after the first of a (spec, constants-class, geometry) is a pool HIT
and the warm path performs ZERO fresh XLA compiles - and reports
latency percentiles for the warm path plus the batched-sweep
throughput ratio.

    python tools/loadgen.py --url http://HOST:PORT --jobs 32
    python tools/loadgen.py --tiny     # self-contained; wired into
                                       # tier-1 next to the serve and
                                       # costmodel tiny smokes

The tiny mode is the serving analog of `tools/chaos.py --matrix`: one
driver invocation that exercises submit -> schedule -> pool ->
sweep-batch -> journal -> /runs end to end and fails loudly if the
warm path regresses into recompiles.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

_SPEC = """---- MODULE LoadTiny ----
EXTENDS Naturals
CONSTANTS MAX
VARIABLES x, y

Init == /\\ x = 0
        /\\ y = 0

Up == /\\ x < MAX
      /\\ x' = x + 1
      /\\ y' = y

Flip == /\\ x > 0
        /\\ y' = 1 - y
        /\\ x' = x

Next == Up \\/ Flip

Spec == Init /\\ [][Next]_<<x, y>>

InRange == x <= MAX
====
"""

_CFG = """CONSTANT MAX = 4
SPECIFICATION
Spec
INVARIANT
InRange
"""


# a long-running chain model (depth = MAX+1 levels): the overload
# mode's "heavy" job class - wide enough in time for deterministic
# preemption windows, tiny in state space
_SLOW_SPEC = """---- MODULE LoadChain ----
EXTENDS Naturals
CONSTANTS MAX
VARIABLES x

Init == x = 0

Up == /\\ x < MAX
      /\\ x' = x + 1

Next == Up

Spec == Init /\\ [][Next]_x

InRange == x <= MAX
====
"""

_SLOW_CFG = """CONSTANT MAX = 600
SPECIFICATION
Spec
INVARIANT
InRange
"""


def _pct(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    k = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[k]


def run_load(url: str, jobs: int, sweep_jobs: int,
             out=sys.stdout) -> dict:
    """Drive `url`: one cold submit, `jobs - 1` warm resubmits, then
    `sweep_jobs` batched sweep submits.  Returns the report dict."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(chunk=16, qcap=256, fpcap=1024)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="load-cold",
                        options=opts)
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["verdict"] == "ok", cold

    warm_lat = []
    pre_compiles = xla_compiles()
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"load-warm-{i}",
                          options=opts)
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["pool_hit"] is True, st
        assert st["result"]["generated"] == cold["result"]["generated"]
    fresh = xla_compiles() - pre_compiles
    assert fresh == 0, f"warm path paid {fresh} fresh XLA compiles"

    # batched sweep: K configs of the class through one dispatch
    sweep = {"const": "MAX", "lo": 1, "hi": 4}
    ids = [
        client.submit(url, _SPEC, _CFG, name=f"load-sweep-{v}",
                      constants={"MAX": 1 + (v % 4)}, sweep=sweep,
                      options=opts)
        for v in range(sweep_jobs)
    ]
    t0 = time.time()
    sts = [client.wait(url, i, timeout=600) for i in ids]
    sweep_s = time.time() - t0
    for st in sts:
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sweep", st

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs, sweep_jobs=sweep_jobs,
        cold_s=round(cold_s, 4),
        warm_p50_s=round(_pct(warm_lat, 0.50), 4),
        warm_p95_s=round(_pct(warm_lat, 0.95), 4),
        warm_fresh_xla_compiles=fresh,
        sweep_wall_s=round(sweep_s, 4),
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"],
                  compiles=stats["pool"]["compiles"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_cache(url: str, jobs: int, in_process: bool,
              out=sys.stdout) -> dict:
    """The --cache mode (ISSUE 13): N IDENTICAL submits against the
    artifact cache.  Submit 1 is the cold population run; submits 2..N
    must be verdict-tier hits - ZERO fresh XLA compiles (CompileMeter)
    AND zero engine dispatches (the pool entry's use count freezes) -
    and their p50/p95 latency is the O(HTTP) number PERF.md round 16
    compares against the 54 ms warm-pool submit."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(chunk=16, qcap=256, fpcap=1024)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="cache-cold",
                        options=opts)
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["verdict"] == "ok", cold
    assert cold["result"]["engine"] == "pool", cold

    def pool_uses():
        # every pooled dispatch is preceded by exactly one pool lookup
        # (uses counts hits; the cold build's own run is covered by
        # the miss/build counters): frozen uses == zero dispatches
        st = client.pool_stats(url)
        return (sum(e["uses"] for e in st["pool"]["entries"])
                + st["pool"]["misses"])

    uses0 = pool_uses()
    pre = xla_compiles() if in_process else None
    hit_lat = []
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        # fine-grained poll (5 ms vs the default 50): the hit path is
        # O(HTTP), so the default poll interval would BE the number
        st = client.wait(
            url,
            client.submit(url, _SPEC, _CFG, name=f"cache-hit-{i}",
                          options=opts),
            poll_s=0.005,
        )
        hit_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "cache", st
        assert st["result"].get("cache_hit") is True, st
        assert st["result"]["generated"] == cold["result"]["generated"]
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, f"cache-hit path paid {fresh} fresh XLA compiles"
    dispatches = pool_uses() - uses0
    assert dispatches == 0, (
        f"cache-hit path dispatched {dispatches} engine run(s)"
    )
    stats = client.pool_stats(url)
    cache = client._get(url + "/cache")
    report = dict(
        jobs=jobs,
        cold_s=round(cold_s, 4),
        hit_p50_s=round(_pct(hit_lat, 0.50), 4),
        hit_p95_s=round(_pct(hit_lat, 0.95), 4),
        hit_fresh_xla_compiles=fresh,
        hit_engine_dispatches=dispatches,
        scheduler_cache_hits=stats["scheduler"]["cache_hits"],
        store=cache["stats"] if cache.get("enabled") else None,
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_sim_load(url: str, jobs: int, in_process: bool,
                 out=sys.stdout) -> dict:
    """The --sim mode (ISSUE 14): the smoke job class under load.
    Submit 1 cold + N-1 warm sim jobs (same spec, DIFFERENT seeds -
    the seed is a batch lane, not key material, so every resubmit
    after the first must be a pool HIT with ZERO fresh XLA compiles),
    then one folded burst submitted together to exercise the vmapped
    seed batch."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(simulate=True, walkers=16, depth=32, fpcap=1024,
                nodeadlock=True)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="sim-cold",
                        options=dict(opts, simseed=0))
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["engine"] == "sim", cold
    assert cold["result"]["verdict"] == "ok", cold

    warm_lat = []
    pre = xla_compiles() if in_process else None
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"sim-warm-{i}",
                          options=dict(opts, simseed=i + 1))
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sim", st
        assert st["result"]["pool_hit"] is True, st
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, f"warm sim path paid {fresh} fresh XLA compiles"

    # a burst submitted together folds into vmapped seed batches
    ids = [client.submit(url, _SPEC, _CFG, name=f"sim-burst-{i}",
                         options=dict(opts, simseed=100 + i))
           for i in range(jobs)]
    t0 = time.time()
    sts = [client.wait(url, i, timeout=600) for i in ids]
    burst_s = time.time() - t0
    for st in sts:
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sim", st

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs,
        cold_s=round(cold_s, 4),
        sim_p50_s=round(_pct(warm_lat, 0.50), 4),
        sim_p95_s=round(_pct(warm_lat, 0.95), 4),
        sim_fresh_xla_compiles=fresh,
        burst_wall_s=round(burst_s, 4),
        transitions=cold["result"]["sim"]["transitions"],
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_infer_load(url: str, jobs: int, in_process: bool,
                   out=sys.stdout) -> dict:
    """The --infer mode (ISSUE 16): the inference job class under
    load.  Submit 1 cold + N-1 warm infer jobs (same spec, DIFFERENT
    seeds - the seed only drives sampled evidence, not key material,
    so every resubmit after the first must be a pool HIT with ZERO
    fresh XLA compiles)."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(infer=True, inferbudget=16, walkers=16, depth=32,
                nodeadlock=True)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="infer-cold",
                        options=dict(opts, simseed=0))
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["engine"] == "infer", cold
    assert cold["result"]["verdict"] == "ok", cold
    funnel = cold["result"]["infer"]
    assert funnel["candidates"] > 0, funnel

    warm_lat = []
    pre = xla_compiles() if in_process else None
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"infer-warm-{i}",
                          options=dict(opts, simseed=i + 1))
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "infer", st
        assert st["result"]["pool_hit"] is True, st
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, (
        f"warm infer path paid {fresh} fresh XLA compiles"
    )

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs,
        cold_s=round(cold_s, 4),
        infer_p50_s=round(_pct(warm_lat, 0.50), 4),
        infer_p95_s=round(_pct(warm_lat, 0.95), 4),
        infer_fresh_xla_compiles=fresh,
        candidates=funnel["candidates"],
        survivors=funnel["survivors"],
        certified=len(funnel["certified"]),
        evidence=funnel["evidence"],
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def run_overload(url: str, jobs: int, in_process: bool,
                 tiny: bool = False, out=sys.stdout) -> dict:
    """The --overload mode (ISSUE 17): the service under sustained
    over-capacity load.  Phases:

    1. clean warm latency - the regression gate against the PR 12
       54 ms warm-submit baseline (zero fresh XLA compiles asserted);
    2. priority preemption - a low-priority checkpointed heavy job is
       preempted by a high-priority arrival, requeued as a -recover
       resume, and its final counters must be BIT-FOR-BIT the
       uninterrupted reference run's (the PR 2/7 contract);
    3. the storm - a heavy "plug" job occupies the worker while a
       burst overruns the admission bound: every rejection must be a
       429 with a Retry-After hint, every accepted job must reach a
       terminal state, a deadlined job expires, a canceled job
       cancels, and a rejected submit resubmitted through the client
       backoff eventually lands;
    4. (full mode) the mixed classes - smoke, sweep, infer, and
       artifact-cache hits - ride the same overloaded server.

    Wants a server with a SMALL admission bound (the in-process
    default here is queue_bound=4; external servers should be started
    with --queue-bound 4)."""
    import os
    import tempfile

    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(chunk=16, qcap=256, fpcap=1024, noartifactcache=True)
    heavy = dict(chunk=16, qcap=256, fpcap=1024, nodeadlock=True,
                 checkpointevery=8, noartifactcache=True)
    ckdir = tempfile.mkdtemp(prefix="jaxtlc-loadgen-overload-")

    bound = client.pool_stats(url)["scheduler"]["queue_bound"]
    assert bound <= 32, (
        f"--overload wants a small admission bound (queue_bound="
        f"{bound}); start the server with --queue-bound 4"
    )

    # -- 1. clean warm latency -------------------------------------------
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="over-cold",
                        options=opts)
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["verdict"] == "ok", cold
    warm_lat = []
    pre = xla_compiles() if in_process else None
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"over-warm-{i}",
                          options=opts)
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["pool_hit"] is True, st
    fresh = (xla_compiles() - pre) if in_process else 0
    assert fresh == 0, f"warm path paid {fresh} fresh XLA compiles"

    # -- 2. preemption + bit-for-bit resume ------------------------------
    ref = client.check(
        url, _SLOW_SPEC, _SLOW_CFG, name="over-ref",
        options=dict(heavy, checkpoint=os.path.join(ckdir, "ref.npz")),
        timeout=600,
    )
    assert ref["state"] == "done", ref
    assert ref["result"]["verdict"] == "ok", ref

    low = {}
    attempts = 0
    for attempt in range(3):
        attempts = attempt + 1
        low_id = client.submit(
            url, _SLOW_SPEC, _SLOW_CFG, name=f"over-low-{attempt}",
            options=dict(heavy, priority=0, checkpoint=os.path.join(
                ckdir, f"low{attempt}.npz")),
        )
        deadline = time.time() + 120
        while client.status(url, low_id)["state"] == "queued":
            assert time.time() < deadline, "heavy job never started"
            time.sleep(0.005)
        hi = client.check(url, _SPEC, _CFG, name=f"over-hi-{attempt}",
                          options=dict(opts, priority=10))
        assert hi["state"] == "done", hi
        low = client.wait(url, low_id, timeout=600)
        assert low["state"] == "done", low
        if low.get("requeues", 0) >= 1:
            break
    assert low.get("requeues", 0) >= 1, (
        f"preemption never landed in {attempts} attempt(s): {low}"
    )
    for k in ("generated", "distinct", "depth", "violation",
              "action_generated"):
        assert low["result"][k] == ref["result"][k], (
            f"resumed {k} diverged: {low['result'][k]} != "
            f"{ref['result'][k]}"
        )

    # -- 3. the storm ----------------------------------------------------
    plug_id = client.submit(
        url, _SLOW_SPEC, _SLOW_CFG, name="over-plug",
        options=dict(heavy, checkpoint=os.path.join(ckdir, "plug.npz")),
    )
    deadline = time.time() + 120
    while client.status(url, plug_id)["state"] == "queued":
        assert time.time() < deadline, "plug job never started"
        time.sleep(0.005)
    # the worker is pinned for the plug's whole wall: a deterministic
    # overload window
    exp_id = client.submit(url, _SPEC, _CFG, name="over-deadline",
                           options=dict(opts, deadline_s=0.25))
    can_id = client.submit(url, _SPEC, _CFG, name="over-cancel",
                           options=opts)
    assert client.cancel(url, can_id)["state"] == "canceled"
    accepted, rejections = [], []
    for i in range(bound + 6):
        try:
            accepted.append(
                client.submit(url, _SPEC, _CFG, name=f"over-burst-{i}",
                              options=opts, retries=0)
            )
        except client.ClientError as e:
            assert e.code == 429, f"rejection was {e.code}, not 429"
            assert (e.retry_after or 0) >= 1, (
                f"429 without a usable Retry-After: {e.retry_after}"
            )
            rejections.append(e.retry_after)
    assert rejections, "overload burst produced no 429 rejections"
    # a rejected submit THROUGH the client's 429 backoff must land
    t0 = time.time()
    retry_id = client.submit(url, _SPEC, _CFG, name="over-retry",
                             options=opts, retries=6)
    resubmit_s = time.time() - t0
    for jid in accepted + [plug_id, retry_id]:
        st = client.wait(url, jid, timeout=600)
        assert st["state"] == "done", st
    exp = client.wait(url, exp_id, timeout=30)
    assert exp["state"] == "expired", exp

    # -- 4. the mixed classes (full mode) --------------------------------
    mixed = {}
    if not tiny:
        sim = client.check(
            url, _SPEC, _CFG, name="over-sim",
            options=dict(simulate=True, walkers=16, depth=32,
                         fpcap=1024, nodeadlock=True, simseed=7),
        )
        assert sim["state"] == "done", sim
        assert sim["result"]["engine"] == "sim", sim
        sweep_ids = [
            client.submit(url, _SPEC, _CFG, name=f"over-sweep-{v}",
                          constants={"MAX": 1 + (v % 4)},
                          sweep={"const": "MAX", "lo": 1, "hi": 4},
                          options=opts)
            for v in range(4)
        ]
        sweeps = [client.wait(url, i, timeout=600) for i in sweep_ids]
        assert all(s["state"] == "done"
                   and s["result"]["engine"] == "sweep"
                   for s in sweeps), sweeps
        inf = client.check(
            url, _SPEC, _CFG, name="over-infer",
            options=dict(infer=True, inferbudget=16, walkers=16,
                         depth=32, nodeadlock=True, simseed=0),
        )
        assert inf["state"] == "done", inf
        assert inf["result"]["engine"] == "infer", inf
        mixed["mixed_classes"] = dict(sim="done", sweep=len(sweeps),
                                      infer="done")
        if in_process:
            cache_opts = dict(chunk=16, qcap=256, fpcap=1024)
            c0 = client.check(url, _SPEC, _CFG, name="over-cache-0",
                              options=cache_opts)
            c1 = client.check(url, _SPEC, _CFG, name="over-cache-1",
                              options=cache_opts)
            assert c1["result"]["engine"] == "cache", c1
            mixed["mixed_classes"]["cache"] = "hit"

    h = client.health(url)
    assert h["status"] == "ok" and h["queued"] == 0, h
    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs, queue_bound=bound,
        cold_s=round(cold_s, 4),
        warm_p50_s=round(_pct(warm_lat, 0.50), 4),
        warm_p95_s=round(_pct(warm_lat, 0.95), 4),
        warm_fresh_xla_compiles=fresh,
        preempt=dict(attempts=attempts,
                     requeues=low.get("requeues", 0), parity=True),
        burst=dict(submitted=bound + 6, accepted=len(accepted),
                   rejected=len(rejections),
                   retry_after_s=[min(rejections), max(rejections)],
                   resubmit_backoff_s=round(resubmit_s, 4)),
        expired=1, canceled=1,
        counters=stats["scheduler"]["sched"],
        **mixed,
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="loadgen")
    p.add_argument("--url", default="",
                   help="a live jaxtlc.serve server; default: start "
                        "one in-process")
    p.add_argument("--jobs", type=int, default=8,
                   help="plain submits of one model (1 cold + N-1 warm)")
    p.add_argument("--sweep-jobs", type=int, default=4,
                   help="sweep submits folded into batched dispatches")
    p.add_argument("--sim", action="store_true",
                   help="smoke job class mode (ISSUE 14): 1 cold + "
                        "N-1 warm sim submits (different seeds, same "
                        "warm engine - zero fresh XLA compiles "
                        "asserted) plus a folded seed-batch burst; "
                        "reports warm sim p50/p95")
    p.add_argument("--infer", action="store_true",
                   help="inference job class mode (ISSUE 16): 1 cold "
                        "+ N-1 warm infer submits (different evidence "
                        "seeds, same warm engine - zero fresh XLA "
                        "compiles asserted); reports warm infer "
                        "p50/p95 and the candidate funnel")
    p.add_argument("--cache", action="store_true",
                   help="incremental re-checking mode (ISSUE 13): N "
                        "identical submits; 1 cold population run, "
                        "N-1 verdict-tier hits asserted to perform "
                        "ZERO fresh XLA compiles and ZERO engine "
                        "dispatches; reports hit p50/p95.  In-process "
                        "servers get a temp store so the run is "
                        "self-contained")
    p.add_argument("--overload", action="store_true",
                   help="overload mode (ISSUE 17): warm-latency gate, "
                        "priority preemption with bit-for-bit resume, "
                        "an admission-bound storm (429 + Retry-After "
                        "on every rejection, client backoff resubmit), "
                        "deadline expiry + cancel, and - without "
                        "--tiny - the mixed job classes on the same "
                        "overloaded server.  In-process servers start "
                        "with queue_bound=4")
    p.add_argument("--tiny", action="store_true",
                   help="tier-1 smoke: in-process server, 4 plain + 4 "
                        "sweep jobs, pool-reuse + zero-compile "
                        "assertions (with --cache: 4 identical "
                        "submits through the artifact cache; with "
                        "--overload: the storm matrix minus the mixed "
                        "classes)")
    args = p.parse_args(argv)
    if args.tiny:
        args.jobs, args.sweep_jobs, args.url = 4, 4, ""

    srv = None
    url = args.url
    token = None
    try:
        if not url:
            if args.cache or args.overload:
                # self-contained store: the assertions need a cache
                # that starts empty and nothing else writes to
                import tempfile

                from jaxtlc.struct import artifacts as arts

                token = arts.configure(
                    tempfile.mkdtemp(prefix="jaxtlc-loadgen-cache-")
                )
            from jaxtlc.serve.server import start_server

            srv = start_server(
                sweep_width=4,
                **(dict(queue_bound=4) if args.overload else {}),
            )
            url = srv.url
        if args.overload:
            report = run_overload(url, args.jobs,
                                  in_process=srv is not None,
                                  tiny=args.tiny)
            ok = (report["warm_fresh_xla_compiles"] == 0
                  and report["burst"]["rejected"] >= 1
                  and report["preempt"]["requeues"] >= 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: overload - "
                  f"{report['burst']['accepted']} accepted + "
                  f"{report['burst']['rejected']} rejected (429 + "
                  f"Retry-After) of {report['burst']['submitted']} "
                  f"burst submits, preempted heavy job resumed "
                  f"bit-for-bit after {report['preempt']['requeues']} "
                  f"requeue(s), 1 expired + 1 canceled, warm p50 "
                  f"{report['warm_p50_s'] * 1000:.1f} ms / p95 "
                  f"{report['warm_p95_s'] * 1000:.1f} ms, 0 fresh "
                  f"compiles on the warm path")
            return 0 if ok else 1
        if args.sim:
            report = run_sim_load(url, args.jobs,
                                  in_process=srv is not None)
            ok = (report["sim_fresh_xla_compiles"] == 0
                  and report["pool"]["hits"] >= args.jobs - 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: "
                  f"{args.jobs} sim submits (1 cold + "
                  f"{args.jobs - 1} warm) + {args.jobs} burst, "
                  f"warm sim p50 {report['sim_p50_s'] * 1000:.1f} ms "
                  f"/ p95 {report['sim_p95_s'] * 1000:.1f} ms, "
                  f"0 fresh compiles on the warm path, "
                  f"{report['scheduler']['batched_jobs']} jobs "
                  f"through {report['scheduler']['batches_run']} "
                  "dispatches")
            return 0 if ok else 1
        if args.infer:
            report = run_infer_load(url, args.jobs,
                                    in_process=srv is not None)
            ok = (report["infer_fresh_xla_compiles"] == 0
                  and report["pool"]["hits"] >= args.jobs - 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: "
                  f"{args.jobs} infer submits (1 cold + "
                  f"{args.jobs - 1} warm), "
                  f"{report['candidates']} candidates -> "
                  f"{report['survivors']} survive -> "
                  f"{report['certified']} certified "
                  f"[{report['evidence']} evidence], "
                  f"warm infer p50 "
                  f"{report['infer_p50_s'] * 1000:.1f} ms "
                  f"/ p95 {report['infer_p95_s'] * 1000:.1f} ms, "
                  f"0 fresh compiles on the warm path")
            return 0 if ok else 1
        if args.cache:
            report = run_cache(url, args.jobs, in_process=srv is not None)
            ok = (report["hit_fresh_xla_compiles"] == 0
                  and report["hit_engine_dispatches"] == 0
                  and report["scheduler_cache_hits"] >= args.jobs - 1)
            print(f"loadgen {'OK' if ok else 'FAILED'}: "
                  f"{args.jobs} identical submits, 1 cold + "
                  f"{args.jobs - 1} verdict-tier hits, hit p50 "
                  f"{report['hit_p50_s'] * 1000:.1f} ms / p95 "
                  f"{report['hit_p95_s'] * 1000:.1f} ms, 0 fresh "
                  f"compiles and 0 engine dispatches on the hit path")
            return 0 if ok else 1
        report = run_load(url, args.jobs, args.sweep_jobs)
    finally:
        if srv is not None:
            srv.shutdown()
        if token is not None:
            from jaxtlc.struct import artifacts as arts

            arts.restore(token)
    ok = (report["warm_fresh_xla_compiles"] == 0
          and report["pool"]["hits"] >= args.jobs - 1)
    print(f"loadgen {'OK' if ok else 'FAILED'}: "
          f"{args.jobs} plain + {args.sweep_jobs} sweep jobs, "
          f"warm p50 {report['warm_p50_s'] * 1000:.1f} ms / "
          f"p95 {report['warm_p95_s'] * 1000:.1f} ms, "
          f"0 fresh compiles on the warm path, "
          f"{report['scheduler']['batched_jobs']} jobs through "
          f"{report['scheduler']['batches_run']} sweep dispatches")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
