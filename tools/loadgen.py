"""Load generator for the checking service (ISSUE 9 CI tooling).

Submits N jobs against a live `jaxtlc.serve` server (or an in-process
one it starts itself), asserts the pool-reuse contract - every submit
after the first of a (spec, constants-class, geometry) is a pool HIT
and the warm path performs ZERO fresh XLA compiles - and reports
latency percentiles for the warm path plus the batched-sweep
throughput ratio.

    python tools/loadgen.py --url http://HOST:PORT --jobs 32
    python tools/loadgen.py --tiny     # self-contained; wired into
                                       # tier-1 next to the serve and
                                       # costmodel tiny smokes

The tiny mode is the serving analog of `tools/chaos.py --matrix`: one
driver invocation that exercises submit -> schedule -> pool ->
sweep-batch -> journal -> /runs end to end and fails loudly if the
warm path regresses into recompiles.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

_REPO = __file__.rsplit("/", 2)[0]
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

_SPEC = """---- MODULE LoadTiny ----
EXTENDS Naturals
CONSTANTS MAX
VARIABLES x, y

Init == /\\ x = 0
        /\\ y = 0

Up == /\\ x < MAX
      /\\ x' = x + 1
      /\\ y' = y

Flip == /\\ x > 0
        /\\ y' = 1 - y
        /\\ x' = x

Next == Up \\/ Flip

Spec == Init /\\ [][Next]_<<x, y>>

InRange == x <= MAX
====
"""

_CFG = """CONSTANT MAX = 4
SPECIFICATION
Spec
INVARIANT
InRange
"""


def _pct(xs, q):
    xs = sorted(xs)
    if not xs:
        return 0.0
    k = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
    return xs[k]


def run_load(url: str, jobs: int, sweep_jobs: int,
             out=sys.stdout) -> dict:
    """Drive `url`: one cold submit, `jobs - 1` warm resubmits, then
    `sweep_jobs` batched sweep submits.  Returns the report dict."""
    from jaxtlc.serve import client
    from jaxtlc.serve.pool import xla_compiles

    opts = dict(chunk=16, qcap=256, fpcap=1024)
    t0 = time.time()
    cold = client.check(url, _SPEC, _CFG, name="load-cold",
                        options=opts)
    cold_s = time.time() - t0
    assert cold["state"] == "done", cold
    assert cold["result"]["verdict"] == "ok", cold

    warm_lat = []
    pre_compiles = xla_compiles()
    for i in range(max(0, jobs - 1)):
        t0 = time.time()
        st = client.check(url, _SPEC, _CFG, name=f"load-warm-{i}",
                          options=opts)
        warm_lat.append(time.time() - t0)
        assert st["state"] == "done", st
        assert st["result"]["pool_hit"] is True, st
        assert st["result"]["generated"] == cold["result"]["generated"]
    fresh = xla_compiles() - pre_compiles
    assert fresh == 0, f"warm path paid {fresh} fresh XLA compiles"

    # batched sweep: K configs of the class through one dispatch
    sweep = {"const": "MAX", "lo": 1, "hi": 4}
    ids = [
        client.submit(url, _SPEC, _CFG, name=f"load-sweep-{v}",
                      constants={"MAX": 1 + (v % 4)}, sweep=sweep,
                      options=opts)
        for v in range(sweep_jobs)
    ]
    t0 = time.time()
    sts = [client.wait(url, i, timeout=600) for i in ids]
    sweep_s = time.time() - t0
    for st in sts:
        assert st["state"] == "done", st
        assert st["result"]["engine"] == "sweep", st

    stats = client.pool_stats(url)
    report = dict(
        jobs=jobs, sweep_jobs=sweep_jobs,
        cold_s=round(cold_s, 4),
        warm_p50_s=round(_pct(warm_lat, 0.50), 4),
        warm_p95_s=round(_pct(warm_lat, 0.95), 4),
        warm_fresh_xla_compiles=fresh,
        sweep_wall_s=round(sweep_s, 4),
        pool=dict(hits=stats["pool"]["hits"],
                  misses=stats["pool"]["misses"],
                  size=stats["pool"]["size"],
                  compiles=stats["pool"]["compiles"]),
        scheduler=dict(
            batches_run=stats["scheduler"]["batches_run"],
            batched_jobs=stats["scheduler"]["batched_jobs"],
        ),
    )
    out.write(json.dumps(report, indent=2, sort_keys=True) + "\n")
    return report


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="loadgen")
    p.add_argument("--url", default="",
                   help="a live jaxtlc.serve server; default: start "
                        "one in-process")
    p.add_argument("--jobs", type=int, default=8,
                   help="plain submits of one model (1 cold + N-1 warm)")
    p.add_argument("--sweep-jobs", type=int, default=4,
                   help="sweep submits folded into batched dispatches")
    p.add_argument("--tiny", action="store_true",
                   help="tier-1 smoke: in-process server, 4 plain + 4 "
                        "sweep jobs, pool-reuse + zero-compile "
                        "assertions")
    args = p.parse_args(argv)
    if args.tiny:
        args.jobs, args.sweep_jobs, args.url = 4, 4, ""

    srv = None
    url = args.url
    if not url:
        from jaxtlc.serve.server import start_server

        srv = start_server(sweep_width=4)
        url = srv.url
    try:
        report = run_load(url, args.jobs, args.sweep_jobs)
    finally:
        if srv is not None:
            srv.shutdown()
    ok = (report["warm_fresh_xla_compiles"] == 0
          and report["pool"]["hits"] >= args.jobs - 1)
    print(f"loadgen {'OK' if ok else 'FAILED'}: "
          f"{args.jobs} plain + {args.sweep_jobs} sweep jobs, "
          f"warm p50 {report['warm_p50_s'] * 1000:.1f} ms / "
          f"p95 {report['warm_p95_s'] * 1000:.1f} ms, "
          f"0 fresh compiles on the warm path, "
          f"{report['scheduler']['batched_jobs']} jobs through "
          f"{report['scheduler']['batches_run']} sweep dispatches")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
